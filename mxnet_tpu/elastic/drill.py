"""Real multi-process drills for the multi-host control plane.

ROADMAP item 4 left one half open: nothing exercised two hosts racing a
manifest commit, a host dying mid-snapshot while peers keep training, or
a hung peer stalling the job. This harness closes it with REAL OS
processes — ``run_drill`` spawns N ``multiprocessing`` children over a
shared tmpdir, each running single-process CPU compute and coordinating
purely through elastic/coordinator.py's filesystem control plane. No
jax.distributed, no SPMD mesh: the compute is a deterministic pure-numpy
toy trainer, so the drill isolates exactly the layer under test (the
control plane is host-side file/process logic and runs identically on a
pod and on this CPU-only container).

Scenarios (tests/test_multihost_drill.py):

    clean            N hosts train + snapshot to completion, then resume
                     onto a DIFFERENT world size; loss trajectory must
                     equal the single-process reference exactly
    kill_host        a non-leader host _exit()s mid-run; survivors detect
                     the dead lease, post a ``peer_dead`` stop, converge
                     on one final step S and snapshot it together
    kill_leader      the leader _exit()s mid-commit — AFTER its ready
                     marker and a fresh commit lease, the worst spot; the
                     next-lowest live rank takes the stale lease over
                     (incremented fence token) and finishes that commit
    commit_race      every host believes it is the leader
                     (``debug_force_leader``): the commit lease
                     serializes them; exactly one manifest per step
    straggler        one host's final ready marker is delayed past the
                     straggler deadline: peers abort cleanly (booked on
                     mx_snapshot_failures_total{source="straggler"}) and
                     retry under the barrier until the marker lands

The toy trainer deliberately keeps everything float64 and in-place, so a
snapshot round-trip is bit-exact and trajectory parity asserts with zero
tolerance budget.

``control_plane_worker`` is the CPU-only mode tools/launch.py is tested
through (tests/test_dist_launch.py): boot, rendezvous via the
coordinator until all ranks are live, heartbeat, clean shutdown — the
launcher's process/env plumbing is exercised end to end even though SPMD
*compute* needs a real multi-host backend.
"""
from __future__ import annotations

import argparse
import json
import multiprocessing
import os
import sys
import time
from typing import Any, Dict, List, Optional

import numpy as _np

from .coordinator import Coordinator
from .run import resume_or_init, run

__all__ = ["ToyTrainer", "ToyFeed", "toy_batch", "reference_losses",
           "run_drill", "control_plane_worker"]

_DIM = 8
_HIDDEN = 16
_BATCH = 16
_LR = 0.05


def toy_batch(cursor: int):
    """Deterministic batch ``cursor`` — same stream on every host and on
    the single-process reference, so data-parallel replicas compute
    identical steps."""
    rng = _np.random.RandomState(10_000 + int(cursor))
    x = rng.randn(_BATCH, _DIM)
    w = _np.sin(_np.arange(_DIM))
    y = _np.tanh(x @ w)[:, None] + 0.1 * rng.randn(_BATCH, 1)
    return x, y


class ToyTrainer:
    """Pure-numpy MLP with analytic gradients and in-place SGD.

    Implements exactly the surface ``elastic.run`` + the snapshot plane
    need — ``step(x, y) -> float``, ``drain()``, ``_t``, and the
    duck-typed ``elastic_state()`` / ``elastic_install()`` extension
    point of elastic/state.py. float64 end to end: a save/restore
    round-trip through the npz chunks is bit-exact."""

    def __init__(self, seed: int = 0):
        rng = _np.random.RandomState(seed)
        self.params: List[_np.ndarray] = [
            rng.randn(_DIM, _HIDDEN) * 0.3,      # param.0
            _np.zeros(_HIDDEN),                  # param.1
            rng.randn(_HIDDEN, 1) * 0.3,         # param.2
            _np.zeros(1),                        # param.3
        ]
        self._t = 0

    def step(self, x, y) -> float:
        w1, b1, w2, b2 = self.params
        h = _np.tanh(x @ w1 + b1)
        pred = h @ w2 + b2
        err = pred - y
        loss = float(_np.mean(err * err))
        n = x.shape[0]
        gpred = 2.0 * err / n
        gw2 = h.T @ gpred
        gb2 = gpred.sum(axis=0)
        gh = gpred @ w2.T * (1.0 - h * h)
        gw1 = x.T @ gh
        gb1 = gh.sum(axis=0)
        for p, g in zip(self.params, (gw1, gb1, gw2, gb2)):
            p -= _LR * g
        self._t += 1
        return loss

    def drain(self):
        pass

    # -- the elastic/state.py duck-typed snapshot surface --------------------

    def elastic_state(self) -> Dict[str, Any]:
        leaves = {f"param.{i}": p for i, p in enumerate(self.params)}
        return {"leaves": leaves,
                "meta": {"format": 1, "kind": "toy", "step": self._t,
                         "dims": [_DIM, _HIDDEN]}}

    def elastic_install(self, meta, fetch, names):
        if meta.get("dims") != [_DIM, _HIDDEN]:
            raise ValueError(f"toy snapshot dims {meta.get('dims')} do not "
                             f"match this build ({[_DIM, _HIDDEN]})")
        for i in range(len(self.params)):
            self.params[i][...] = fetch(f"param.{i}")
        self._t = int(meta["step"])


class ToyFeed:
    """Cursor-based infinite feed over :func:`toy_batch` with the
    ``state_dict``/``load_state_dict`` surface ``elastic.run`` rewinds on
    resume — the cursor rides the snapshot meta, so a resumed trajectory
    replays the exact batch sequence."""

    def __init__(self):
        self._cursor = 0

    def __iter__(self):
        while True:
            batch = toy_batch(self._cursor)
            self._cursor += 1
            yield batch

    def state_dict(self):
        return {"cursor": int(self._cursor)}

    def load_state_dict(self, state):
        self._cursor = int(state["cursor"])


def reference_losses(num_steps: int, seed: int = 0) -> List[float]:
    """The single-process ground-truth trajectory every drill resume is
    asserted against."""
    trainer = ToyTrainer(seed=seed)
    feed = iter(ToyFeed())
    losses = []
    for _ in range(int(num_steps)):
        x, y = next(feed)
        losses.append(trainer.step(x, y))
    return losses


# ---------------------------------------------------------------------------
# Drill host process
# ---------------------------------------------------------------------------

def _host_main(cfg: Dict[str, Any]):
    """One drill host (multiprocessing spawn target): join the control
    plane, resume-or-init from the shared root, train under elastic.run
    with the coordinator attached, write a JSON report. Never imports
    jax — a drill child is pure host-side numpy + file IO."""
    rank = int(cfg["rank"])
    root = cfg["root"]
    if cfg.get("telemetry"):
        from .. import telemetry as _telem
        _telem.enable()
    goodput_on = bool(cfg.get("goodput"))
    if goodput_on:
        # arm the goodput ledger over the shared root: each host appends
        # its per-step waterfall to <root>/telemetry/host-<rank>.tsr; the
        # parent aggregates after the drill (straggler lane). note_step
        # keeps the child jax-free — the ledger is pure host arithmetic.
        from ..telemetry import goodput as _goodput
        _goodput.enable(root=root, rank=rank)
    coord = Coordinator(
        root, rank,
        lease_timeout=float(cfg.get("lease_timeout", 1.0)),
        straggler_timeout=float(cfg.get("straggler_timeout", 8.0)),
        heartbeat_interval=0.0,
        partition_ownership=True,
        poll_interval=0.01)
    if cfg.get("die_in_commit_step") is not None:
        coord.debug_exit_after_marker = int(cfg["die_in_commit_step"])
    if cfg.get("marker_delay") is not None:
        coord.debug_marker_delay = (int(cfg["marker_delay"][0]),
                                    float(cfg["marker_delay"][1]))
    coord.debug_force_leader = bool(cfg.get("force_leader"))
    coord.join()
    # rendezvous: do not start stepping until the whole world is live,
    # so generation/ownership starts identical on every host
    deadline = time.monotonic() + 30.0
    while len(coord.view().live) < int(cfg["world"]):
        if time.monotonic() >= deadline:
            os._exit(7)
        time.sleep(0.02)
    feed = ToyFeed()
    mgr, trainer, start, outcome = resume_or_init(
        root, ToyTrainer, feed=feed,
        max_to_keep=int(cfg.get("max_to_keep", 10)),
        save_interval_steps=int(cfg["save_every"]),
        coordinator=coord)
    die_at = cfg.get("die_at_step")
    step_sleep = float(cfg.get("step_sleep", 0.0))
    losses: Dict[str, float] = {}

    def on_step(t, loss):
        losses[str(t)] = float(loss)
        if goodput_on:
            from ..telemetry import goodput as _goodput
            _goodput.note_step(source="drill")
        if die_at is not None and t >= int(die_at):
            os._exit(3)         # simulated hard host loss: no cleanup
        if step_sleep:
            time.sleep(step_sleep)

    res = run(trainer, feed, int(cfg["num_steps"]), manager=mgr,
              on_step=on_step, coordinator=coord)
    report = {"rank": rank, "start": int(start), "outcome": outcome,
              "final_step": int(res["step"]),
              "preempted": bool(res["preempted"]),
              "stop": res["stop"], "losses": losses,
              "generation": int(coord.generation),
              "fence": int(coord.fence)}
    if cfg.get("telemetry"):
        from .. import telemetry as _telem
        m = _telem.get_metric("mx_snapshot_failures_total")
        report["straggler_aborts"] = float(m.get("straggler")) if m else 0.0
        m = _telem.get_metric("mx_hosts_live")
        report["hosts_live"] = float(m.get("elastic")) if m else None
    if goodput_on:
        from ..telemetry import goodput as _goodput
        t = _goodput.totals()
        report["goodput"] = {"steps": t["steps"],
                             "wall_seconds": t["wall_seconds"],
                             "goodput_ratio": t["goodput_ratio"],
                             "generation": t["generation"]}
    path = os.path.join(cfg["report_dir"], f"report-{rank:05d}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f)
    os.replace(tmp, path)
    coord.leave()


def run_drill(root: str, world: int, num_steps: int, save_every: int = 5,
              scenario: Optional[Dict[str, Any]] = None,
              timeout: float = 120.0, report_tag: str = "r0",
              telemetry: bool = True, goodput: bool = False,
              **overrides) -> Dict[str, Any]:
    """Spawn ``world`` real OS processes over the shared ``root`` and run
    one drill phase. ``scenario`` maps PER-RANK overrides, e.g.
    ``{2: {"die_at_step": 6}}``; ``overrides`` apply to every host
    (lease_timeout, straggler_timeout, step_sleep, ...).

    With ``goodput=True`` every host arms the goodput ledger over the
    shared root; after the drill ``telemetry.goodput.aggregate(root)``
    merges the per-host series (the straggler-detection lane: slow one
    rank via ``scenario={r: {"step_sleep": ...}}`` and the merged summary
    flags it).

    Returns ``{"exitcodes": [...], "reports": {rank: {...}}}`` — a rank
    that died mid-drill has its scripted exit code and no report."""
    report_dir = os.path.join(root, f"reports-{report_tag}")
    os.makedirs(report_dir, exist_ok=True)
    ctx = multiprocessing.get_context("spawn")
    procs = []
    for r in range(int(world)):
        cfg = {"root": root, "rank": r, "world": int(world),
               "num_steps": int(num_steps), "save_every": int(save_every),
               "report_dir": report_dir, "telemetry": bool(telemetry),
               "goodput": bool(goodput)}
        cfg.update(overrides)
        cfg.update((scenario or {}).get(r, {}))
        p = ctx.Process(target=_host_main, args=(cfg,),
                        name=f"mx-drill-host-{r}")
        p.start()
        procs.append(p)
    deadline = time.monotonic() + float(timeout)
    for p in procs:
        p.join(timeout=max(0.1, deadline - time.monotonic()))
    for p in procs:
        if p.is_alive():
            p.kill()
            p.join(timeout=5.0)
    reports: Dict[int, Dict[str, Any]] = {}
    for name in os.listdir(report_dir):
        if name.startswith("report-") and name.endswith(".json"):
            with open(os.path.join(report_dir, name)) as f:
                rec = json.load(f)
            reports[int(rec["rank"])] = rec
    return {"exitcodes": [p.exitcode for p in procs], "reports": reports}


# ---------------------------------------------------------------------------
# Control-plane-only worker (tools/launch.py smoke mode)
# ---------------------------------------------------------------------------

def control_plane_worker(root: str, beats: int = 5,
                         rendezvous_timeout: float = 60.0) -> int:
    """Boot → rendezvous → heartbeat → clean shutdown, using ONLY the
    control plane. Rank/world come from the env tools/launch.py sets
    (``MXNET_TPU_RANK`` / ``MXNET_TPU_NUM_WORKERS``), so running this
    under the launcher exercises its process/env plumbing on CPU without
    any SPMD compute. Writes ``ok_<rank>`` into ``root`` on success;
    returns a shell exit code."""
    rank = int(os.environ.get("MXNET_TPU_RANK", "0"))
    world = int(os.environ.get("MXNET_TPU_NUM_WORKERS", "1"))
    coord = Coordinator(root, rank, lease_timeout=10.0, poll_interval=0.02)
    coord.join()
    deadline = time.monotonic() + float(rendezvous_timeout)
    while len(coord.view().live) < world:
        if time.monotonic() >= deadline:
            print(f"rank {rank}: rendezvous timed out "
                  f"({len(coord.view(bump=False).live)}/{world} live)",
                  file=sys.stderr)
            return 2
        time.sleep(0.02)
    for i in range(int(beats)):
        coord.heartbeat(i, force=True)
        time.sleep(0.01)
    view = coord.view(bump=False)
    with open(os.path.join(root, f"ok_{rank}"), "w") as f:
        json.dump({"rank": rank, "world": world,
                   "generation": view.generation, "live": view.live}, f)
    coord.leave()
    return 0


def _main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="multi-host control-plane drill worker")
    ap.add_argument("--control-plane", action="store_true",
                    help="run the launcher smoke mode (rendezvous only)")
    ap.add_argument("--root", required=True,
                    help="shared control-plane/snapshot directory")
    ap.add_argument("--beats", type=int, default=5)
    args = ap.parse_args(argv)
    if args.control_plane:
        return control_plane_worker(args.root, beats=args.beats)
    ap.error("only --control-plane mode has a CLI; use run_drill() "
             "from Python for full drills")
    return 2


if __name__ == "__main__":
    sys.exit(_main())
