"""On-disk snapshot layout + atomic manifest commit (mxnet_tpu.elastic).

A snapshot of training step K is a directory

    <root>/step-<K>/shard-<p>.npz     per-process chunk payloads
    <root>/step-<K>/shard-<p>.json    per-process chunk index
    <root>/step-<K>/manifest.json     commit marker (merged index + meta)

Every process writes ONLY the array chunks it is the designated owner of
(its addressable shards with ``replica_id == 0`` — the same no-gather
ownership rule the ZeRO sharded update establishes, arXiv:2004.13336), so
a snapshot never materializes a gathered copy of the model on any host.
``manifest.json`` is the atomicity token: it is written to a temp file and
``os.replace``d into place only after every expected shard file landed, so
a snapshot directory without it is by definition incomplete (a preempted
writer) and is ignored by restore and pruned by retention.

The manifest records everything restore needs WITHOUT the saving process:

  - ``leaves``: global shape + dtype per named leaf;
  - ``chunks``: for each leaf, the ``[[start, stop], ...]`` index region
    each npz entry covers — chunks tile the global array exactly, so a
    restore onto a *different* mesh assembles the full host array and
    re-places it under the new sharding (elastic re-scale);
  - ``meta``: the trainer-level host state (step, schedule counters, loss
    scale, RNG is a leaf, ZeRO bucket plans, mesh shape, program
    fingerprint) — see elastic/state.py for the exact schema.
"""
from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

import numpy as _np

from ..base import MXNetError

__all__ = ["step_dirname", "step_path", "parse_step", "all_complete_steps",
           "latest_complete_step", "write_shard", "commit", "load", "prune",
           "SnapshotReader"]

FORMAT = 1
_STEP_PREFIX = "step-"
MANIFEST = "manifest.json"


def step_dirname(step: int) -> str:
    return f"{_STEP_PREFIX}{int(step):08d}"


def step_path(root: str, step: int) -> str:
    return os.path.join(root, step_dirname(step))


def parse_step(name: str) -> Optional[int]:
    if not name.startswith(_STEP_PREFIX):
        return None
    try:
        return int(name[len(_STEP_PREFIX):])
    except ValueError:
        return None


def all_complete_steps(root: str) -> List[int]:
    """Steps whose manifest committed (incomplete dirs are invisible)."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        step = parse_step(name)
        if step is not None and \
                os.path.exists(os.path.join(root, name, MANIFEST)):
            steps.append(step)
    return sorted(steps)


def latest_complete_step(root: str) -> Optional[int]:
    steps = all_complete_steps(root)
    return steps[-1] if steps else None


# ---------------------------------------------------------------------------
# Writer side (runs on the background snapshot thread, never the step path)
# ---------------------------------------------------------------------------

def write_shard(sdir: str, process_index: int, entries) -> int:
    """Write one process's chunk payloads + index.

    ``entries``: iterable of ``(leaf_name, index, array, global_shape,
    dtype)`` where ``index`` is ``[[start, stop], ...]`` per dim of the
    global leaf and ``array`` is the host chunk covering exactly that
    region. Returns the payload byte count."""
    os.makedirs(sdir, exist_ok=True)
    payload: Dict[str, _np.ndarray] = {}
    chunks, leaves = [], {}
    nbytes = 0
    for i, (name, index, arr, gshape, dtype) in enumerate(entries):
        key = f"c{i}"
        arr = _np.asarray(arr)
        payload[key] = arr
        nbytes += arr.nbytes
        chunks.append({"name": name, "key": key,
                       "index": [[int(a), int(b)] for a, b in index]})
        leaves[name] = {"shape": [int(d) for d in gshape],
                        "dtype": str(dtype)}
    base = os.path.join(sdir, f"shard-{int(process_index):05d}")
    tmp = base + ".npz.tmp"
    with open(tmp, "wb") as f:
        _np.savez(f, **payload)
    os.replace(tmp, base + ".npz")
    tmp = base + ".json.tmp"
    with open(tmp, "w") as f:
        json.dump({"process": int(process_index), "chunks": chunks,
                   "leaves": leaves, "nbytes": int(nbytes)}, f)
    os.replace(tmp, base + ".json")
    return nbytes


def commit(sdir: str, step: int, meta: Dict[str, Any],
           expected_processes: int = 1, timeout: float = 120.0
           ) -> Dict[str, Any]:
    """Merge the per-process chunk indexes and atomically write
    ``manifest.json`` — the snapshot exists only once this returns.

    Single-controller runs commit immediately; in multi-controller SPMD
    process 0 calls this after writing its own shard and polls (bounded by
    ``timeout``) for the other processes' index files."""
    deadline = time.monotonic() + timeout
    while True:
        shard_jsons = sorted(n for n in os.listdir(sdir)
                             if n.startswith("shard-") and n.endswith(".json"))
        if len(shard_jsons) >= expected_processes:
            break
        if time.monotonic() >= deadline:
            raise MXNetError(
                f"snapshot commit timed out: {len(shard_jsons)}/"
                f"{expected_processes} shard indexes present in {sdir}")
        time.sleep(0.05)
    leaves: Dict[str, Any] = {}
    chunks: Dict[str, List[Dict[str, Any]]] = {}
    nbytes = 0
    for name in shard_jsons:
        with open(os.path.join(sdir, name)) as f:
            shard = json.load(f)
        npz = name[:-len(".json")] + ".npz"
        nbytes += int(shard.get("nbytes", 0))
        leaves.update(shard["leaves"])
        for c in shard["chunks"]:
            chunks.setdefault(c["name"], []).append(
                {"file": npz, "key": c["key"], "index": c["index"]})
    man = {"format": FORMAT, "step": int(step), "meta": meta,
           "leaves": leaves, "chunks": chunks, "nbytes": int(nbytes)}
    tmp = os.path.join(sdir, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(man, f)
    os.replace(tmp, os.path.join(sdir, MANIFEST))
    return man


def load(root: str, step: int) -> Dict[str, Any]:
    path = os.path.join(step_path(root, step), MANIFEST)
    if not os.path.exists(path):
        raise MXNetError(f"no complete snapshot for step {step} in {root}")
    with open(path) as f:
        man = json.load(f)
    if man.get("format") != FORMAT:
        raise MXNetError(
            f"snapshot format {man.get('format')!r} unsupported "
            f"(this build reads format {FORMAT})")
    return man


def prune(root: str, max_to_keep: int) -> List[int]:
    """Retention: drop the oldest COMPLETE snapshots beyond ``max_to_keep``
    and any incomplete directory older than the newest complete one (a
    preempted writer's leftovers). Never touches the newest snapshot."""
    complete = all_complete_steps(root)
    removed = []
    if max_to_keep > 0:
        for step in complete[:-max_to_keep] if len(complete) > max_to_keep \
                else []:
            shutil.rmtree(step_path(root, step), ignore_errors=True)
            removed.append(step)
    if complete:
        for name in os.listdir(root):
            step = parse_step(name)
            if step is not None and step < complete[-1] and \
                    not os.path.exists(os.path.join(root, name, MANIFEST)):
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)
    return removed


# ---------------------------------------------------------------------------
# Reader side (restore)
# ---------------------------------------------------------------------------

class SnapshotReader:
    """Assemble full host arrays for named leaves of one snapshot.

    The fetch interface elastic/state.py's ``install`` consumes:
    ``reader(name)`` returns the GLOBAL numpy array for that leaf,
    stitched from however many per-process chunks the saving mesh
    produced — the resharding pivot for save-on-N / resume-on-M."""

    def __init__(self, root: str, step: int,
                 manifest: Optional[Dict[str, Any]] = None):
        self._dir = step_path(root, step)
        self.manifest = manifest if manifest is not None else load(root, step)
        self._npz: Dict[str, Any] = {}

    @property
    def names(self):
        return set(self.manifest["leaves"])

    def _file(self, npz_name: str):
        f = self._npz.get(npz_name)
        if f is None:
            f = self._npz[npz_name] = _np.load(
                os.path.join(self._dir, npz_name))
        return f

    def __call__(self, name: str) -> _np.ndarray:
        spec = self.manifest["leaves"].get(name)
        if spec is None:
            raise KeyError(name)
        shape = tuple(spec["shape"])
        out = _np.empty(shape, dtype=_np.dtype(spec["dtype"]))
        covered = 0
        for c in self.manifest["chunks"].get(name, ()):
            chunk = self._file(c["file"])[c["key"]]
            idx = tuple(slice(a, b) for a, b in c["index"])
            out[idx] = chunk
            covered += int(chunk.size)
        if covered != out.size:
            raise MXNetError(
                f"snapshot leaf {name!r}: chunks cover {covered} of "
                f"{out.size} elements — corrupt or partial snapshot")
        return out

    def close(self):
        for f in self._npz.values():
            try:
                f.close()
            except Exception:
                pass
        self._npz.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
