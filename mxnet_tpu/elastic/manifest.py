"""On-disk snapshot layout + atomic manifest commit (mxnet_tpu.elastic).

A snapshot of training step K is a directory

    <root>/step-<K>/shard-<p>.npz     per-process chunk payloads
    <root>/step-<K>/shard-<p>.json    per-process chunk index
    <root>/step-<K>/manifest.json     commit marker (merged index + meta)

Every process writes ONLY the array chunks it is the designated owner of
(its addressable shards with ``replica_id == 0`` — the same no-gather
ownership rule the ZeRO sharded update establishes, arXiv:2004.13336), so
a snapshot never materializes a gathered copy of the model on any host.
``manifest.json`` is the atomicity token: it is written to a temp file and
``os.replace``d into place only after every expected shard file landed, so
a snapshot directory without it is by definition incomplete (a preempted
writer) and is ignored by restore and pruned by retention.

The manifest records everything restore needs WITHOUT the saving process:

  - ``leaves``: global shape + dtype per named leaf;
  - ``chunks``: for each leaf, the ``[[start, stop], ...]`` index region
    each npz entry covers — chunks tile the global array exactly, so a
    restore onto a *different* mesh assembles the full host array and
    re-places it under the new sharding (elastic re-scale);
  - ``meta``: the trainer-level host state (step, schedule counters, loss
    scale, RNG is a leaf, ZeRO bucket plans, mesh shape, program
    fingerprint) — see elastic/state.py for the exact schema.

Failure hardening (docs/reliability.md): every write path fsyncs file
contents before its ``os.replace`` and fsyncs the directory after — the
rename alone orders the metadata but not the data, so a power cut could
otherwise commit a manifest pointing at torn shards. All IO runs under
``faults.io_retry`` (bounded backoff+jitter on ``OSError``/injected
faults, ``MXNET_TPU_IO_RETRIES``), and ``commit`` serializes concurrent
committers through a lease file with a fencing token: exactly one writer
finalizes a step's manifest, a fenced-out writer raises ``MXNetError``
instead of interleaving, and a crashed committer's stale lease is taken
over with an incremented token.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

import numpy as _np

from .. import faults as _faults
from ..base import MXNetError, env

__all__ = ["step_dirname", "step_path", "parse_step", "all_complete_steps",
           "latest_complete_step", "write_shard", "commit", "load", "prune",
           "SnapshotReader"]

FORMAT = 1
_STEP_PREFIX = "step-"
MANIFEST = "manifest.json"
LEASE = "commit.lease"
READY_PREFIX = "ready-"

env.declare("MXNET_TPU_PRUNE_GRACE", 30.0, float,
            "Retention liveness grace in seconds: prune skips an "
            "incomplete snapshot directory whose commit lease or ready "
            "markers were written within this window — another live host "
            "may still be mid-write (0 disables the check)")


def _fsync_file(f):
    f.flush()
    os.fsync(f.fileno())


def _fsync_dir(path: str):
    """Make a completed rename durable: fsync the containing directory.
    Best-effort no-op on platforms where directories can't be opened."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def step_dirname(step: int) -> str:
    return f"{_STEP_PREFIX}{int(step):08d}"


def step_path(root: str, step: int) -> str:
    return os.path.join(root, step_dirname(step))


def parse_step(name: str) -> Optional[int]:
    if not name.startswith(_STEP_PREFIX):
        return None
    try:
        return int(name[len(_STEP_PREFIX):])
    except ValueError:
        return None


def all_complete_steps(root: str) -> List[int]:
    """Steps whose manifest committed (incomplete dirs are invisible)."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        step = parse_step(name)
        if step is not None and \
                os.path.exists(os.path.join(root, name, MANIFEST)):
            steps.append(step)
    return sorted(steps)


def latest_complete_step(root: str) -> Optional[int]:
    steps = all_complete_steps(root)
    return steps[-1] if steps else None


# ---------------------------------------------------------------------------
# Writer side (runs on the background snapshot thread, never the step path)
# ---------------------------------------------------------------------------

def write_shard(sdir: str, process_index: int, entries) -> int:
    """Write one process's chunk payloads + index.

    ``entries``: iterable of ``(leaf_name, index, array, global_shape,
    dtype)`` where ``index`` is ``[[start, stop], ...]`` per dim of the
    global leaf and ``array`` is the host chunk covering exactly that
    region. Returns the payload byte count."""
    os.makedirs(sdir, exist_ok=True)
    payload: Dict[str, _np.ndarray] = {}
    chunks, leaves = [], {}
    nbytes = 0
    for i, (name, index, arr, gshape, dtype) in enumerate(entries):
        key = f"c{i}"
        arr = _np.asarray(arr)
        payload[key] = arr
        nbytes += arr.nbytes
        chunks.append({"name": name, "key": key,
                       "index": [[int(a), int(b)] for a, b in index]})
        leaves[name] = {"shape": [int(d) for d in gshape],
                        "dtype": str(dtype)}
    base = os.path.join(sdir, f"shard-{int(process_index):05d}")

    def _write_payload():
        tmp = base + ".npz.tmp"
        with open(tmp, "wb") as f:
            _np.savez(f, **payload)
            _fsync_file(f)
        os.replace(tmp, base + ".npz")

    def _write_index():
        tmp = base + ".json.tmp"
        with open(tmp, "w") as f:
            json.dump({"process": int(process_index), "chunks": chunks,
                       "leaves": leaves, "nbytes": int(nbytes)}, f)
            _fsync_file(f)
        os.replace(tmp, base + ".json")

    _faults.io_retry("elastic.write_shard", _write_payload)
    _faults.io_retry("elastic.write_shard", _write_index)
    _fsync_dir(sdir)
    return nbytes


# -- commit lease: exactly one concurrent committer finalizes a step --------

def _lease_path(sdir: str, lease_name: str = LEASE) -> str:
    return os.path.join(sdir, lease_name)


def _read_lease(sdir: str, lease_name: str = LEASE) -> Dict[str, Any]:
    try:
        with open(_lease_path(sdir, lease_name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _write_lease_to(path: str, owner: str, token: int):
    with open(path, "w") as f:
        json.dump({"owner": owner, "token": int(token),
                   "ts": time.time()}, f)
        _fsync_file(f)


def _acquire_lease(sdir: str, owner: str, stale_after: float,
                   lease_name: str = LEASE) -> int:
    """Take the step dir's commit lease; returns this holder's fencing
    token. Exactly one of N concurrent committers wins via O_EXCL create
    (shared-filesystem atomic); losers raise ``MXNetError``. A lease whose
    holder died (older than ``stale_after`` seconds) is taken over with an
    INCREMENTED token, so a crashed committer cannot block commits forever
    while the fenced-out stale holder can never finalize — ``commit``
    re-verifies owner+token immediately before the manifest rename.

    ``lease_name`` lets other control-plane state reuse the same fenced
    mutual exclusion (elastic/coordinator.py serializes generation-epoch
    updates through ``generation.lock`` with exactly this protocol)."""
    path = _lease_path(sdir, lease_name)
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        pass
    else:
        with os.fdopen(fd, "w") as f:
            json.dump({"owner": owner, "token": 1, "ts": time.time()}, f)
            _fsync_file(f)
        return 1
    holder = _read_lease(sdir, lease_name)
    age = time.time() - float(holder.get("ts", 0.0))
    if age <= stale_after and holder:
        raise MXNetError(
            f"snapshot commit lease for {sdir} is held by "
            f"{holder.get('owner')!r} (age {age:.1f}s, token "
            f"{holder.get('token')}): exactly one committer may finalize "
            "a step; this writer lost the race")
    token = int(holder.get("token", 0)) + 1
    tmp = path + f".{owner}.tmp"
    _write_lease_to(tmp, owner, token)
    os.replace(tmp, path)
    # concurrent takeovers race on the replace; last write wins — re-read
    # to learn whether WE hold it now
    if _read_lease(sdir, lease_name).get("owner") != owner:
        raise MXNetError(
            f"lost the stale-lease takeover race for {sdir}")
    return token


def _verify_lease(sdir: str, owner: str, token: int,
                  lease_name: str = LEASE):
    cur = _read_lease(sdir, lease_name)
    if cur.get("owner") != owner or int(cur.get("token", -1)) != int(token):
        raise MXNetError(
            f"commit fenced out: lease for {sdir} now held by "
            f"{cur.get('owner')!r} (token {cur.get('token')}, ours "
            f"{token}) — a newer committer took over; this manifest "
            "must not land")


def _release_lease(sdir: str, owner: str, lease_name: str = LEASE):
    if _read_lease(sdir, lease_name).get("owner") == owner:
        try:
            os.unlink(_lease_path(sdir, lease_name))
        except OSError:
            pass


def commit(sdir: str, step: int, meta: Dict[str, Any],
           expected_processes: int = 1, timeout: float = 120.0,
           lease_timeout: float = 30.0,
           ranks: Optional[Sequence[int]] = None) -> Dict[str, Any]:
    """Merge the per-process chunk indexes and atomically write
    ``manifest.json`` — the snapshot exists only once this returns.

    Single-controller runs commit immediately; in multi-controller SPMD
    process 0 calls this after writing its own shard and polls (bounded by
    ``timeout``) for the other processes' index files. When the caller
    knows the exact membership (the elastic coordinator's two-phase
    commit), ``ranks`` pins the merge to precisely those shard indexes —
    a stale shard left by a host fenced out at an older generation is
    neither waited for nor merged (it would overlap the live set's
    re-partitioned chunks).

    Concurrent committers (a split-brain rank 0 after an elastic restart,
    or racing supervisors) are serialized by a lease file with a fencing
    token: the winner's token is recorded in the manifest (``fence``), the
    loser raises ``MXNetError`` without touching the manifest, and a lease
    older than ``lease_timeout`` seconds is treated as a crashed holder
    and taken over."""
    required = None if ranks is None else sorted(
        f"shard-{int(r):05d}.json" for r in ranks)
    deadline = time.monotonic() + timeout
    while True:
        present = {n for n in os.listdir(sdir)
                   if n.startswith("shard-") and n.endswith(".json")}
        if required is not None:
            shard_jsons = [n for n in required if n in present]
            if len(shard_jsons) == len(required):
                break
        else:
            shard_jsons = sorted(present)
            if len(shard_jsons) >= expected_processes:
                break
        if time.monotonic() >= deadline:
            raise MXNetError(
                f"snapshot commit timed out: {len(shard_jsons)}/"
                f"{expected_processes if required is None else len(required)}"
                f" shard indexes present in {sdir}")
        time.sleep(0.05)
    owner = f"{os.getpid()}.{threading.get_ident()}.{uuid.uuid4().hex[:8]}"
    token = _acquire_lease(sdir, owner, lease_timeout)
    try:
        if os.path.exists(os.path.join(sdir, MANIFEST)):
            raise MXNetError(
                f"step {step} is already committed in {sdir}: another "
                "writer won the fence; exactly one committer finalizes "
                "a snapshot")
        leaves: Dict[str, Any] = {}
        chunks: Dict[str, List[Dict[str, Any]]] = {}
        nbytes = 0
        for name in shard_jsons:
            with open(os.path.join(sdir, name)) as f:
                shard = json.load(f)
            npz = name[:-len(".json")] + ".npz"
            nbytes += int(shard.get("nbytes", 0))
            leaves.update(shard["leaves"])
            for c in shard["chunks"]:
                chunks.setdefault(c["name"], []).append(
                    {"file": npz, "key": c["key"], "index": c["index"]})
        man = {"format": FORMAT, "step": int(step), "meta": meta,
               "leaves": leaves, "chunks": chunks, "nbytes": int(nbytes),
               "fence": int(token)}

        def _write_manifest():
            tmp = os.path.join(sdir, MANIFEST + ".tmp")
            with open(tmp, "w") as f:
                json.dump(man, f)
                _fsync_file(f)
            # the fencing check: a stale holder that slept past its lease
            # gets caught HERE, after its payload write but before the
            # commit rename becomes visible
            _verify_lease(sdir, owner, token)
            os.replace(tmp, os.path.join(sdir, MANIFEST))
            _fsync_dir(sdir)

        _faults.io_retry("elastic.commit", _write_manifest)
        return man
    finally:
        _release_lease(sdir, owner)


def load(root: str, step: int) -> Dict[str, Any]:
    path = os.path.join(step_path(root, step), MANIFEST)
    if not os.path.exists(path):
        raise MXNetError(f"no complete snapshot for step {step} in {root}")

    def _read():
        with open(path) as f:
            return json.load(f)

    man = _faults.io_retry("elastic.read", _read)
    if man.get("format") != FORMAT:
        raise MXNetError(
            f"snapshot format {man.get('format')!r} unsupported "
            f"(this build reads format {FORMAT})")
    return man


def _writer_active(sdir: str, grace: float) -> bool:
    """Liveness check behind prune safety: a manifest-less directory is
    only debris if nobody is mid-write in it. A commit lease or a
    coordinator ready marker stamped within ``grace`` seconds means
    another live host is still producing this snapshot — pruning it out
    from under that writer turns a slow snapshot into a corrupt one. The
    recorded wall-clock ``ts`` fields are used (not file mtimes), so
    stale debris from a crashed writer ages out and is swept normally."""
    if grace <= 0.0:
        return False
    now = time.time()
    holder = _read_lease(sdir)
    if holder and now - float(holder.get("ts", 0.0)) <= grace:
        return True
    try:
        names = os.listdir(sdir)
    except OSError:
        return False
    for name in names:
        if not (name.startswith(READY_PREFIX) and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(sdir, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if now - float(rec.get("ts", 0.0)) <= grace:
            return True
    return False


def prune(root: str, max_to_keep: int,
          active_grace: Optional[float] = None) -> List[int]:
    """Retention: drop the oldest COMPLETE snapshots beyond ``max_to_keep``
    and any incomplete directory older than the newest complete one (a
    preempted writer's leftovers). Never touches the newest snapshot, and
    never an incomplete directory another live host is still writing
    (fresh lease/ready-marker within ``active_grace`` seconds — default
    ``MXNET_TPU_PRUNE_GRACE``; see :func:`_writer_active`)."""
    grace = float(env.get("MXNET_TPU_PRUNE_GRACE")
                  if active_grace is None else active_grace)
    complete = all_complete_steps(root)
    removed = []
    if max_to_keep > 0:
        for step in complete[:-max_to_keep] if len(complete) > max_to_keep \
                else []:
            shutil.rmtree(step_path(root, step), ignore_errors=True)
            removed.append(step)
    if complete:
        for name in os.listdir(root):
            step = parse_step(name)
            if step is not None and step < complete[-1] and \
                    not os.path.exists(os.path.join(root, name, MANIFEST)) \
                    and not _writer_active(os.path.join(root, name), grace):
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)
    return removed


# ---------------------------------------------------------------------------
# Reader side (restore)
# ---------------------------------------------------------------------------

class SnapshotReader:
    """Assemble full host arrays for named leaves of one snapshot.

    The fetch interface elastic/state.py's ``install`` consumes:
    ``reader(name)`` returns the GLOBAL numpy array for that leaf,
    stitched from however many per-process chunks the saving mesh
    produced — the resharding pivot for save-on-N / resume-on-M.

    Multi-host restore validation: pass ``expected_generation`` /
    ``expected_fence`` to refuse a manifest committed under a different
    group epoch or without a fencing token (elastic/coordinator.py's
    restore path supplies both). ``read_region`` assembles just one
    index region, opening ONLY the chunk files that intersect it — each
    host reads its owned chunks, never the whole snapshot; ``files_read``
    records which payload files were actually opened."""

    def __init__(self, root: str, step: int,
                 manifest: Optional[Dict[str, Any]] = None,
                 expected_generation: Optional[int] = None,
                 expected_fence: Optional[int] = None):
        self._dir = step_path(root, step)
        self.manifest = manifest if manifest is not None else load(root, step)
        self._npz: Dict[str, Any] = {}
        self.files_read: set = set()
        if expected_fence is not None and \
                int(self.manifest.get("fence", -1)) != int(expected_fence):
            raise MXNetError(
                f"snapshot step {step}: manifest fence "
                f"{self.manifest.get('fence')!r} != expected "
                f"{expected_fence} — written by a different (possibly "
                "fenced-out) committer; refusing to restore")
        if expected_generation is not None:
            got = self.manifest.get("meta", {}).get("generation")
            if got is None or int(got) != int(expected_generation):
                raise MXNetError(
                    f"snapshot step {step}: manifest generation {got!r} "
                    f"!= expected {expected_generation} — committed under "
                    "a different group epoch; refusing to restore")

    @property
    def names(self):
        return set(self.manifest["leaves"])

    def _file(self, npz_name: str):
        f = self._npz.get(npz_name)
        if f is None:
            f = self._npz[npz_name] = _faults.io_retry(
                "elastic.read", _np.load, os.path.join(self._dir, npz_name))
            self.files_read.add(npz_name)
        return f

    def read_region(self, name: str, region) -> _np.ndarray:
        """Assemble only ``region`` (``[[start, stop], ...]`` per dim) of
        leaf ``name``, touching only the chunk files that intersect it —
        the owned-chunk restore path for multi-host resume."""
        spec = self.manifest["leaves"].get(name)
        if spec is None:
            raise KeyError(name)
        region = [(int(a), int(b)) for a, b in region]
        shape = tuple(b - a for a, b in region)
        out = _np.empty(shape, dtype=_np.dtype(spec["dtype"]))
        covered = 0
        for c in self.manifest["chunks"].get(name, ()):
            lo = [max(a, ca) for (a, _), (ca, _) in zip(region, c["index"])]
            hi = [min(b, cb) for (_, b), (_, cb) in zip(region, c["index"])]
            if any(l >= h for l, h in zip(lo, hi)):
                continue
            chunk = self._file(c["file"])[c["key"]]
            src = tuple(slice(l - ca, h - ca) for l, h, (ca, _)
                        in zip(lo, hi, c["index"]))
            dst = tuple(slice(l - a, h - a) for l, h, (a, _)
                        in zip(lo, hi, region))
            out[dst] = chunk[src]
            covered += int(_np.prod([h - l for l, h in zip(lo, hi)]))
        if covered != out.size:
            raise MXNetError(
                f"snapshot leaf {name!r} region {region}: chunks cover "
                f"{covered} of {out.size} elements — corrupt or partial "
                "snapshot")
        return out

    def __call__(self, name: str) -> _np.ndarray:
        spec = self.manifest["leaves"].get(name)
        if spec is None:
            raise KeyError(name)
        shape = tuple(spec["shape"])
        out = _np.empty(shape, dtype=_np.dtype(spec["dtype"]))
        covered = 0
        for c in self.manifest["chunks"].get(name, ()):
            chunk = self._file(c["file"])[c["key"]]
            idx = tuple(slice(a, b) for a, b in c["index"])
            out[idx] = chunk
            covered += int(chunk.size)
        if covered != out.size:
            raise MXNetError(
                f"snapshot leaf {name!r}: chunks cover {covered} of "
                f"{out.size} elements — corrupt or partial snapshot")
        return out

    def close(self):
        for f in self._npz.values():
            try:
                f.close()
            except Exception:
                pass
        self._npz.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
