"""Shared-filesystem multi-host control plane (mxnet_tpu.elastic).

The reference framework's cross-node story is a ps-lite scheduler plus
worker/server processes; the TPU-native cluster has no scheduler — every
host is an equal jax.distributed process. What replaces the scheduler is
this coordinator: a small amount of fenced state on the snapshot
filesystem (the one piece of infrastructure an elastic fleet always
shares) that gives N hosts membership, leader election, a coordinated
stop, and a two-phase cross-host snapshot commit — all of it pure
host-side file IO, so the control plane runs identically on a pod and on
a CPU-only CI container (tests/test_multihost_drill.py drives it with
real OS processes).

Layout, under ``<root>/coord/``:

    members/host-<rank>.json   heartbeat: {rank, pid, generation, fence,
                               step, ts} — rewritten atomically every
                               ``heartbeat_interval``; a record whose
                               ``ts`` is older than ``lease_timeout`` is
                               a DEAD host (lease expiry, the PR 13 rule)
    generation.json            the group epoch: {generation, live}. Any
                               observed membership change (join, leave,
                               lease expiry) bumps ``generation`` under
                               the ``generation.lock`` fencing lease, so
                               the number is monotonic and every host at
                               the same generation agrees on ``live``
    stop.json                  coordinated-stop intent (O_EXCL create:
                               the first poster wins)
    stop-ack-<rank>.json       phase-1 quiesce acks; the final stop step
                               S = max over live members' ack steps

and per snapshot step dir (next to the shard files):

    ready-<rank>.json          two-phase commit marker: {rank, step,
                               generation, chunk_index, fence, live}

Two-phase commit: every host writes ONLY its owned chunks plus its ready
marker; the elected leader (lowest live rank, fenced by manifest.py's
commit lease) assembles the global manifest only once every member of
the marker-stamped live set has posted a marker for the same (step,
generation). A straggler deadline aborts the snapshot cleanly — booked
on ``mx_snapshot_failures_total{source="straggler"}`` — rather than
committing a hole; the step dir stays manifest-less (invisible to
restore) and retention sweeps it once its markers go stale.

All coordinator IO threads through ``faults.io_retry`` with three
injection points (``elastic.heartbeat`` / ``elastic.barrier`` /
``elastic.marker``), so the chaos suite can replay dead-peer detection,
rejoin, commit races and straggler aborts deterministically
(docs/reliability.md).
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import weakref
import zlib
from typing import Any, Callable, Dict, List, Optional

from ..base import MXNetError, env
from .. import faults as _faults
from .. import telemetry as _telem
from ..telemetry import goodput as _goodput
from ..telemetry import tracing as _tracing
from . import manifest as _manifest

__all__ = ["Coordinator", "GroupView", "StragglerTimeout", "HangWatchdog",
           "statusz_view"]

COORD_DIR = "coord"
MEMBERS_DIR = "members"
GENERATION = "generation.json"
GEN_LOCK = "generation.lock"
STOP = "stop.json"
READY_PREFIX = "ready-"

env.declare("MXNET_TPU_COORD_LEASE", 10.0, float,
            "Coordinator membership lease in seconds: a host whose "
            "heartbeat record is older than this is declared dead (its "
            "departure bumps the group generation)")
env.declare("MXNET_TPU_COORD_STRAGGLER", 60.0, float,
            "Cross-host snapshot commit deadline in seconds: a live "
            "member whose ready marker does not land within this aborts "
            "the snapshot cleanly (mx_snapshot_failures_total"
            "{source=straggler}) instead of committing a hole")


class StragglerTimeout(MXNetError):
    """A cross-host snapshot commit was aborted: a member of the
    generation's live set never posted its ready marker (or posted one
    from a different generation) within the straggler deadline. The step
    directory has no manifest — restore never sees a hole."""


def _host_name(rank: int) -> str:
    return f"host-{int(rank):05d}.json"


def _ready_name(rank: int) -> str:
    return f"{READY_PREFIX}{int(rank):05d}.json"


def _ack_name(rank: int) -> str:
    return f"stop-ack-{int(rank):05d}.json"


def _write_json_atomic(path: str, payload: Dict[str, Any]):
    # tmp name is per-thread: the run loop and the background snapshot
    # writer both heartbeat; a shared tmp path would let one truncate
    # the other's half-written record before its rename
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str) -> Dict[str, Any]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


class GroupView:
    """One generation-stamped observation of the group: who is live (a
    fresh heartbeat lease), who is dead (lease expired), and the leader
    (lowest live rank). Plain data — safe to ship to /statusz."""

    def __init__(self, generation: int, members: Dict[int, Dict[str, Any]],
                 live: List[int], dead: List[int]):
        self.generation = int(generation)
        self.members = members
        self.live = sorted(int(r) for r in live)
        self.dead = sorted(int(r) for r in dead)

    @property
    def leader(self) -> Optional[int]:
        return self.live[0] if self.live else None

    def as_dict(self) -> Dict[str, Any]:
        return {"generation": self.generation, "live": self.live,
                "dead": self.dead, "leader": self.leader,
                "steps": {str(r): m.get("step")
                          for r, m in sorted(self.members.items())}}


class HangWatchdog:
    """Wall-clock deadline on a blocking section (DispatchWindow drain,
    a commit barrier, heartbeat IO that stopped completing). Rides the
    anomaly plane: on expiry it books ``mx_hang_watchdog_fires_total``,
    dumps the flight recorder (when tracing is armed) and — in its
    default ``action="exit"`` mode — ends the process with a one-line
    diagnosis instead of hanging the fleet forever. ``action="flag"``
    (tests, advisory use) only sets ``fired``."""

    def __init__(self, timeout: float, what: str = "drain",
                 action: str = "exit", on_fire: Optional[Callable] = None):
        self.timeout = float(timeout)
        self.what = str(what)
        self.action = action
        self.on_fire = on_fire
        self.fired = False
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _watch(self):
        if not self._done.wait(self.timeout):
            self._fire()

    def _fire(self):
        self.fired = True
        diagnosis = (f"mx_hang_watchdog: {self.what!r} exceeded its "
                     f"{self.timeout:.1f}s wall-clock deadline — dumping "
                     "the flight recorder and exiting rather than hanging "
                     "the fleet")
        if _telem._ENABLED:
            _telem.record_hang_watchdog(self.what)
        if _tracing._ENABLED:
            _tracing.event("mx.hang_watchdog", what=self.what,
                           timeout=self.timeout)
            try:
                _tracing.dump_flight_recorder(reason=f"hang:{self.what}")
            except Exception:  # the dump must never mask the diagnosis  # mxlint: disable=broad-except
                pass
        print(diagnosis, file=sys.stderr, flush=True)
        if self.on_fire is not None:
            self.on_fire(self.what)
        if self.action == "exit":
            os._exit(86)

    def __enter__(self):
        self._done.clear()
        self.fired = False
        self._thread = threading.Thread(
            target=self._watch, daemon=True,
            name=f"mx-hang-watchdog-{self.what}")
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._done.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None
        return False


class _NullWatchdog:
    fired = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


# live coordinators for /statusz (weakrefs: the debug plane must never
# keep a finished job's coordinator alive)
_REGISTRY: "weakref.WeakValueDictionary[int, Coordinator]" = \
    weakref.WeakValueDictionary()
_REGISTRY_LOCK = threading.Lock()
_REGISTRY_SEQ = [0]


def statusz_view() -> Dict[str, Any]:
    """Group view of the most recently constructed live coordinator
    (telemetry.statusz() merges this under ``"coordinator"``). Read-only:
    never bumps the generation."""
    with _REGISTRY_LOCK:
        items = sorted(_REGISTRY.items())
    if not items:
        return {}
    coord = items[-1][1]
    view = coord.view(bump=False)
    d = view.as_dict()
    d["rank"] = coord.rank
    d["fence"] = coord.fence
    return d


class Coordinator:
    """One host's handle on the shared-filesystem control plane.

    ``rank`` is this host's stable worker index (tools/launch.py's
    MXNET_TPU_RANK). ``lease_timeout`` is the membership lease;
    ``heartbeat_interval`` throttles heartbeat/stop-poll IO on the step
    path (0 = every call). ``partition_ownership=True`` makes this host
    write only the snapshot leaves it owns under the generation's live
    set (the drill's replicated-model mode; SPMD meshes already shard
    ownership by ``replica_id == 0`` and keep it False).
    """

    def __init__(self, root: str, rank: int, *,
                 lease_timeout: Optional[float] = None,
                 heartbeat_interval: float = 0.0,
                 straggler_timeout: Optional[float] = None,
                 watchdog_timeout: Optional[float] = None,
                 partition_ownership: bool = False,
                 poll_interval: float = 0.02,
                 clock: Callable[[], float] = time.time):
        self.root = os.path.abspath(root)
        self.rank = int(rank)
        self.lease_timeout = float(env.get("MXNET_TPU_COORD_LEASE")
                                   if lease_timeout is None else lease_timeout)
        self.heartbeat_interval = float(heartbeat_interval)
        self.straggler_timeout = float(
            env.get("MXNET_TPU_COORD_STRAGGLER")
            if straggler_timeout is None else straggler_timeout)
        self.watchdog_timeout = watchdog_timeout
        self.partition_ownership = bool(partition_ownership)
        self.poll_interval = float(poll_interval)
        self._clock = clock
        self.generation = 0
        self.fence = 0           # generation at (re)join: monotonic per root
        self._joined = False
        self._last_beat = float("-inf")   # throttle (clock domain)
        self._last_beat_ok: Optional[float] = None  # staleness (monotonic)
        self._stop_seen: Optional[Dict[str, Any]] = None
        self._dead_seen: set = set()
        self._live_seen: set = set()
        # test/drill hooks (documented in drill.py): crash simulation
        self.debug_exit_after_marker: Optional[int] = None
        self.debug_marker_delay: Optional[tuple] = None  # (step, seconds)
        self.debug_force_leader = False
        self._cdir = os.path.join(self.root, COORD_DIR)
        self._mdir = os.path.join(self._cdir, MEMBERS_DIR)
        os.makedirs(self._mdir, exist_ok=True)
        with _REGISTRY_LOCK:
            _REGISTRY_SEQ[0] += 1
            _REGISTRY[_REGISTRY_SEQ[0]] = self

    # -- generation epoch (fenced read-modify-write) -------------------------

    def _gen_record(self) -> Dict[str, Any]:
        rec = _read_json(os.path.join(self._cdir, GENERATION))
        return {"generation": int(rec.get("generation", 0)),
                "live": [int(r) for r in rec.get("live", [])]}

    def _update_generation(self, mutate) -> Dict[str, Any]:
        """Fenced generation.json update: ``mutate(cur)`` returns the new
        record (or None to leave it unchanged). Serialized through the
        GEN_LOCK lease (the PR 13 fence machinery) so concurrent bumps
        from racing observers coalesce instead of interleaving."""
        owner = f"{self.rank}.{os.getpid()}.{threading.get_ident()}"

        def _locked_update():
            token = _manifest._acquire_lease(
                self._cdir, owner, self.lease_timeout,
                lease_name=GEN_LOCK)
            try:
                cur = self._gen_record()
                new = mutate(cur)
                if new is None:
                    return cur
                new["generation"] = max(int(new["generation"]),
                                        cur["generation"])
                new["ts"] = self._clock()
                new["fence"] = int(token)
                _write_json_atomic(os.path.join(self._cdir, GENERATION), new)
                return new
            finally:
                _manifest._release_lease(self._cdir, owner,
                                         lease_name=GEN_LOCK)

        deadline = time.monotonic() + max(2.0, 2 * self.lease_timeout)
        while True:
            try:
                return _faults.io_retry("elastic.barrier", _locked_update)
            except MXNetError:
                # lost the lock race (a fresh lease held by a peer): the
                # peer's update is as good as ours — re-read and retry the
                # mutation against the newer record until the deadline
                if time.monotonic() >= deadline:
                    raise
                time.sleep(self.poll_interval)

    # -- membership ----------------------------------------------------------

    def join(self) -> int:
        """Register this host: bump the group generation (fenced), record
        the bumped value as this incarnation's fence token, and write the
        first heartbeat. Rejoining after being declared dead bumps the
        generation again — a monotonically higher fence every time."""
        def _mutate(cur):
            live = sorted(set(cur["live"]) | {self.rank})
            return {"generation": cur["generation"] + 1, "live": live}

        rec = self._update_generation(_mutate)
        self.generation = rec["generation"]
        self.fence = rec["generation"]
        self._joined = True
        if _goodput._ENABLED:
            # goodput ring records carry the group epoch they were written
            # under — how an evicted host's partial series still merges
            # without a hole
            _goodput.set_generation(self.generation)
        self._sweep_expired_members()
        self.heartbeat(step=None, force=True)
        return self.generation

    def _sweep_expired_members(self):
        """Garbage-collect heartbeat files whose lease already expired —
        debris from a previous incarnation of the job. Safe to race with
        a merely-slow host: its next heartbeat rewrites the file (and
        rejoins if peers evicted it in the meantime)."""
        now = self._clock()
        try:
            names = os.listdir(self._mdir)
        except OSError:
            return
        for name in names:
            if not name.startswith("host-") or not name.endswith(".json"):
                continue
            path = os.path.join(self._mdir, name)
            rec = _read_json(path)
            if rec and now - float(rec.get("ts", 0.0)) > self.lease_timeout:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def leave(self):
        """Clean shutdown: drop the heartbeat record and this rank from
        the live set (peers otherwise wait a full lease for the expiry)."""
        if not self._joined:
            return
        self._joined = False
        try:
            os.unlink(os.path.join(self._mdir, _host_name(self.rank)))
        except OSError:
            pass

        def _mutate(cur):
            if self.rank not in cur["live"]:
                return None
            live = [r for r in cur["live"] if r != self.rank]
            return {"generation": cur["generation"] + 1, "live": live}

        try:
            self._update_generation(_mutate)
        except MXNetError:
            pass            # best effort: lease expiry covers a lost leave

    def heartbeat(self, step: Optional[int] = None,
                  force: bool = False) -> bool:
        """Refresh this host's membership lease (throttled to
        ``heartbeat_interval``). A failed write after retries does NOT
        raise — the host keeps training while peers see a stale lease —
        but it is returned as False and ages ``heartbeat_staleness()``.
        Detects being declared dead (this rank missing from the epoch's
        live set) and rejoins with a bumped generation."""
        now = self._clock()
        if not force and now - self._last_beat < self.heartbeat_interval:
            return True
        self._last_beat = now
        payload = {"rank": self.rank, "pid": os.getpid(),
                   "generation": self.generation, "fence": self.fence,
                   "step": None if step is None else int(step), "ts": now}
        path = os.path.join(self._mdir, _host_name(self.rank))
        try:
            _faults.io_retry("elastic.heartbeat", _write_json_atomic,
                             path, payload)
        except (OSError, MXNetError):
            return False
        self._last_beat_ok = time.monotonic()
        rec = self._gen_record()
        if self._joined and rec["generation"] > 0 \
                and self.rank not in rec["live"]:
            # peers expired our lease while heartbeats were failing:
            # rejoin under a NEW (higher) generation + fence
            self.join()
        return True

    def heartbeat_staleness(self) -> float:
        """Seconds since this host's last SUCCESSFUL heartbeat write
        (the self-side hang signal the watchdog reads)."""
        if self._last_beat_ok is None:
            return float("inf")
        return time.monotonic() - self._last_beat_ok

    def view(self, bump: bool = True) -> GroupView:
        """Read every member record and classify live/dead by lease
        expiry. When the observed live set differs from the epoch record
        and ``bump`` is True, the generation is bumped (fenced) — dead-
        peer detection and late joins both advance the epoch exactly
        once no matter how many hosts observe them."""
        now = self._clock()
        members: Dict[int, Dict[str, Any]] = {}
        try:
            names = os.listdir(self._mdir)
        except OSError:
            names = []
        for name in names:
            if not name.startswith("host-") or not name.endswith(".json"):
                continue
            rec = _read_json(os.path.join(self._mdir, name))
            if "rank" in rec:
                members[int(rec["rank"])] = rec
        live = [r for r, m in members.items()
                if now - float(m.get("ts", 0.0)) <= self.lease_timeout]
        dead = [r for r in members if r not in live]
        rec = self._gen_record()
        generation = rec["generation"]
        if bump and sorted(live) != sorted(rec["live"]):
            def _mutate(cur):
                if sorted(cur["live"]) == sorted(live):
                    return None          # a peer already recorded it
                return {"generation": cur["generation"] + 1,
                        "live": sorted(live)}

            generation = self._update_generation(_mutate)["generation"]
        if self._joined and self.rank in live:
            self.generation = generation
            if _goodput._ENABLED:
                _goodput.set_generation(generation)
        self._live_seen.update(live)
        v = GroupView(generation, members, live, dead)
        if _telem._ENABLED:
            _telem.record_hosts_live(len(v.live), generation)
        return v

    def is_leader(self) -> bool:
        return self.view(bump=False).leader == self.rank

    # -- leaf ownership (drill / replicated-model partition) -----------------

    def owns(self, name: str) -> bool:
        """Deterministic leaf-ownership partition over the CURRENT
        epoch's live set: every host at the same generation computes the
        same owner for every leaf, so chunks never overlap and never
        leave a hole. Mesh-sharded leaves don't need this (replica_id 0
        already partitions them); it exists for replicated/host leaves
        when ``partition_ownership`` is on."""
        rec = self._gen_record()
        live = sorted(rec["live"]) or [self.rank]
        owner = live[zlib.crc32(name.encode()) % len(live)]
        return owner == self.rank

    # -- coordinated stop ----------------------------------------------------

    def _stop_stale(self, rec: Dict[str, Any]) -> bool:
        """A stop intent from a PREVIOUS incarnation of the job (its
        generation predates this host's join fence) is history, not an
        instruction — every restart bumps the generation at join, so a
        leftover stop.json can never re-stop the relaunched fleet."""
        return int(rec.get("generation", 0)) < self.fence

    def post_stop(self, step: int, reason: str = "preempted") \
            -> Dict[str, Any]:
        """Post the stop intent (first poster wins; every later post
        returns the existing intent). Peers observe it at their next step
        boundary and everyone converges on one final step S. The intent
        carries a generation-scoped ``id`` that acks reference, so a
        resolved stop from an earlier incarnation can never be confused
        with the current one."""
        path = os.path.join(self._cdir, STOP)
        payload = {"step": int(step), "rank": self.rank,
                   "generation": self.generation, "reason": str(reason),
                   "id": f"g{self.generation}.r{self.rank}",
                   "ts": self._clock()}

        def _post():
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                cur = _read_json(path)
                if cur and not self._stop_stale(cur):
                    return cur
                # a stale intent from a finished incarnation: replace it
                _write_json_atomic(path, payload)
                return payload
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            return payload

        out = _faults.io_retry("elastic.barrier", _post)
        self._stop_seen = out
        if _tracing._ENABLED:
            _tracing.event("mx.coord.stop", step=int(out.get("step", step)),
                           rank=int(out.get("rank", self.rank)),
                           reason=str(out.get("reason", reason)))
        return out

    def stop_posted(self) -> Optional[Dict[str, Any]]:
        if self._stop_seen is not None:
            return self._stop_seen
        rec = _read_json(os.path.join(self._cdir, STOP))
        if rec and not self._stop_stale(rec):
            self._stop_seen = rec
        return self._stop_seen

    def step_poll(self, step: int) -> Optional[Dict[str, Any]]:
        """The per-step-boundary coordinator hook ``elastic.run`` calls:
        refresh the heartbeat, observe a posted stop intent, and detect
        dead peers (a newly expired lease posts a ``peer_dead`` stop so
        the survivors converge on a final snapshot). All IO is throttled
        by ``heartbeat_interval``; returns the stop intent or None."""
        throttled = (self._clock() - self._last_beat
                     < self.heartbeat_interval)
        self.heartbeat(step)
        stop = self._stop_seen
        if stop is None and not throttled:
            stop = self.stop_posted()
        if stop is None and not throttled:
            v = self.view()
            # only a peer THIS incarnation saw live can die on it: a
            # stale heartbeat file left behind by a previous (finished)
            # job must not stop the relaunched fleet at its first step
            newly_dead = [r for r in v.dead if r in self._live_seen
                          and r not in self._dead_seen]
            if newly_dead:
                self._dead_seen.update(newly_dead)
                if _goodput._ENABLED:
                    # incident path (once per eviction): score the fleet
                    # from the on-disk series and flight-record whether
                    # the dead peer was the straggler
                    _goodput.on_eviction(newly_dead, root=self.root)
                stop = self.post_stop(step, reason="peer_dead")
        return stop

    def resolve_stop(self, step: int, timeout: Optional[float] = None) -> int:
        """Phase-1 quiesce: post this host's ack at its current step,
        then wait until every LIVE member has acked (dead peers are
        excluded as their leases expire). Returns the agreed final step
        ``S = max(live acks, stop intent step)`` — callers with
        ``step < S`` run exactly ``S - step`` more steps before the
        final snapshot, so every survivor snapshots the same S."""
        deadline = time.monotonic() + (self.straggler_timeout
                                       if timeout is None else float(timeout))
        stop = self.stop_posted() or {}
        stop_id = stop.get("id")
        ack_path = os.path.join(self._cdir, _ack_name(self.rank))
        _faults.io_retry(
            "elastic.barrier", _write_json_atomic, ack_path,
            {"rank": self.rank, "step": int(step), "stop_id": stop_id,
             "generation": self.generation, "ts": self._clock()})
        while True:
            self.heartbeat(step)
            v = self.view()
            acks = {}
            for r in v.live:
                rec = _read_json(os.path.join(self._cdir, _ack_name(r)))
                # acks reference the stop intent they answer: a leftover
                # ack from a PREVIOUS incarnation's stop must not satisfy
                # this barrier
                if rec and rec.get("stop_id") == stop_id:
                    acks[r] = int(rec.get("step", 0))
            if v.live and all(r in acks for r in v.live):
                s = max(list(acks.values()) + [int(stop.get("step", 0))])
                if _tracing._ENABLED:
                    _tracing.event("mx.coord.stop_resolved", step=s,
                                   generation=v.generation)
                return s
            if time.monotonic() >= deadline:
                missing = [r for r in v.live if r not in acks]
                raise MXNetError(
                    f"coordinated stop did not resolve: live members "
                    f"{missing} never acked within the deadline")
            self._check_self_stale()
            time.sleep(self.poll_interval)

    # -- two-phase cross-host snapshot commit --------------------------------

    def write_marker(self, sdir: str, step: int, nbytes: int) -> int:
        """Phase 1 of the commit: after writing its owned chunks, every
        host posts ``ready-<rank>.json`` stamped with the (step,
        generation) it wrote under, its fence, and the live set the
        ownership partition was computed from. Returns the generation.

        A step that already HAS a manifest is history: re-entering the
        commit path for it (e.g. a relaunched job whose final step
        coincides with the committed one) must not clobber the markers
        the manifest was validated against."""
        if os.path.exists(os.path.join(sdir, _manifest.MANIFEST)):
            return self.generation
        v = self.view()
        if self.debug_marker_delay is not None \
                and int(self.debug_marker_delay[0]) == int(step):
            time.sleep(float(self.debug_marker_delay[1]))
        payload = {"rank": self.rank, "step": int(step),
                   "generation": v.generation, "fence": self.fence,
                   "chunk_index": int(self.rank),
                   "file": f"shard-{self.rank:05d}.npz",
                   "nbytes": int(nbytes), "live": v.live,
                   "ts": self._clock()}
        _faults.io_retry("elastic.marker", _write_json_atomic,
                         os.path.join(sdir, _ready_name(self.rank)), payload)
        if self.debug_exit_after_marker is not None \
                and int(self.debug_exit_after_marker) == int(step):
            # crash simulation (kill-leader-mid-commit drill): leave a
            # fresh commit lease behind, exactly like a holder that died
            # between taking the lease and the manifest rename
            _manifest._write_lease_to(
                os.path.join(sdir, _manifest.LEASE + ".crash.tmp"),
                f"crashed-{self.rank}", 1)
            os.replace(os.path.join(sdir, _manifest.LEASE + ".crash.tmp"),
                       os.path.join(sdir, _manifest.LEASE))
            os._exit(40 + self.rank)
        return v.generation

    def _markers(self, sdir: str) -> Dict[int, Dict[str, Any]]:
        out = {}
        try:
            names = os.listdir(sdir)
        except OSError:
            return out
        for name in names:
            if name.startswith(READY_PREFIX) and name.endswith(".json"):
                rec = _read_json(os.path.join(sdir, name))
                if "rank" in rec:
                    out[int(rec["rank"])] = rec
        return out

    def commit_snapshot(self, sdir: str, step: int, meta: Dict[str, Any],
                        timeout: Optional[float] = None) -> Dict[str, Any]:
        """Phase 2: converge on exactly one generation-stamped global
        manifest. Every host calls this after ``write_marker``; whoever
        the CURRENT view says is leader assembles the manifest once all
        required markers for (step, generation) exist — so if the leader
        dies mid-commit the next-lowest live rank takes over, fenced by
        the manifest commit lease (a stale lease is taken over with an
        incremented token; the dead leader's manifest can never land).
        Aborts via :class:`StragglerTimeout` when a required marker is
        still missing (or stamped with a foreign generation) at the
        deadline."""
        t0 = time.perf_counter()
        deadline = t0 + (self.straggler_timeout if timeout is None
                         else float(timeout))
        my_gen = None
        while True:
            self.heartbeat(step)
            if os.path.exists(os.path.join(sdir, _manifest.MANIFEST)):
                man = _manifest.load(self.root, int(step))
                self.validate_manifest(man, int(step))
                seconds = time.perf_counter() - t0
                if _telem._ENABLED:
                    _telem.record_commit_barrier(seconds)
                if _tracing._ENABLED:
                    _tracing.record_span("mx.coord.commit_barrier", t0,
                                         time.perf_counter(), step=int(step),
                                         generation=man["meta"].get(
                                             "generation"))
                return man
            markers = self._markers(sdir)
            mine = markers.get(self.rank)
            if my_gen is None and mine is not None:
                my_gen = int(mine.get("generation", self.generation))
            v = self.view()
            if mine is not None and (v.leader == self.rank
                                     or self.debug_force_leader):
                required = [int(r) for r in mine.get("live", v.live)]
                have = {r: m for r, m in markers.items() if r in required}
                gens = {int(m.get("generation", -1)) for m in have.values()}
                if len(have) == len(required) and gens == {my_gen}:
                    meta2 = dict(meta)
                    meta2["generation"] = my_gen
                    meta2["members"] = sorted(required)
                    try:
                        man = _manifest.commit(
                            sdir, int(step), meta2,
                            expected_processes=len(required),
                            lease_timeout=self.lease_timeout,
                            ranks=required)
                    except MXNetError:
                        # lost the commit race (another fenced committer —
                        # a second leader, or a stale-lease holder not yet
                        # expired): the manifest check at the top of the
                        # loop picks up the winner's commit
                        time.sleep(self.poll_interval)
                        continue
                    continue        # return via the manifest-exists path
                if gens - {my_gen} and len(have) == len(required):
                    self._abort_straggler(
                        sdir, step,
                        f"markers span generations {sorted(gens)} "
                        f"(ours {my_gen})")
            if time.perf_counter() >= deadline:
                missing = []
                if mine is not None:
                    required = [int(r) for r in mine.get("live", [])]
                    missing = [r for r in required if r not in markers]
                self._abort_straggler(
                    sdir, step,
                    f"missing ready markers from ranks {missing}"
                    if missing else "no manifest within the deadline")
            self._check_self_stale()
            time.sleep(self.poll_interval)

    def _abort_straggler(self, sdir: str, step: int, why: str):
        """Clean abort: book the straggler, leave NO manifest (the dir
        stays invisible to restore and is swept by retention once its
        markers go stale)."""
        if _telem._ENABLED:
            _telem.counter(
                "mx_snapshot_failures_total",
                "Interval snapshots skipped after exhausting IO retries",
                ("source",)).labels("straggler").inc()
        if _tracing._ENABLED:
            _tracing.event("mx.coord.straggler_abort", step=int(step),
                           why=why)
        raise StragglerTimeout(
            f"cross-host snapshot commit aborted at step {step}: {why} "
            f"(straggler deadline {self.straggler_timeout:.1f}s; dir "
            f"{sdir} stays manifest-less)")

    # -- restore-side validation --------------------------------------------

    def validate_manifest(self, man: Dict[str, Any], step: int):
        """Generation + fence validation for restore paths: the manifest
        must carry a fencing token, its generation may not be from the
        future of this root's epoch, and any ready markers still on disk
        for the step must agree with the manifest's (step, generation) —
        a manifest assembled from mixed-generation markers never
        validates."""
        fence = man.get("fence")
        if not isinstance(fence, int) or fence < 1:
            raise MXNetError(
                f"snapshot step {step}: manifest carries no commit fence "
                "token — refused (written by a pre-coordinator writer or "
                "tampered)")
        gen = man.get("meta", {}).get("generation")
        if gen is not None:
            cur = self._gen_record()["generation"]
            if cur and int(gen) > cur:
                raise MXNetError(
                    f"snapshot step {step}: manifest generation {gen} is "
                    f"ahead of this root's epoch {cur} — mixed snapshot "
                    "roots or a wiped coord dir; refusing to restore")
            members = man.get("meta", {}).get("members") or []
            for rank, rec in self._markers(
                    _manifest.step_path(self.root, int(step))).items():
                if rank in members and (int(rec.get("step", -1)) != int(step)
                                        or int(rec.get("generation", -1))
                                        != int(gen)):
                    raise MXNetError(
                        f"snapshot step {step}: ready marker of rank "
                        f"{rank} is stamped (step {rec.get('step')}, "
                        f"generation {rec.get('generation')}) but the "
                        f"manifest says (step {step}, generation {gen}) "
                        "— inconsistent commit; refusing to restore")

    # -- hang watchdog -------------------------------------------------------

    def watchdog(self, what: str = "drain"):
        """Armed :class:`HangWatchdog` over a blocking section when
        ``watchdog_timeout`` is configured, else a no-op context."""
        if self.watchdog_timeout is None:
            return _NullWatchdog()
        return HangWatchdog(self.watchdog_timeout, what=what)

    def _check_self_stale(self):
        """Inside wait loops: our OWN heartbeat not landing for a full
        watchdog deadline means the shared filesystem (or this process)
        is wedged — fire the watchdog rather than silently stalling the
        group."""
        if self.watchdog_timeout is None:
            return
        if self.heartbeat_staleness() > float(self.watchdog_timeout):
            HangWatchdog(0.0, what="heartbeat")._fire()

    # -- lifecycle -----------------------------------------------------------

    def close(self):
        self.leave()

    def __enter__(self):
        if not self._joined:
            self.join()
        return self

    def __exit__(self, *exc):
        self.close()
        return False
