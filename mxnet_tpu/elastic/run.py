"""Supervised elastic training: boot-from-latest, preemption, run loop.

The contract a preemptible job wants is small:

    mgr, trainer, start, outcome = elastic.resume_or_init(dir, make_trainer,
                                                          feed=feed)
    elastic.run(trainer, feed, num_steps, manager=mgr)

Every worker calls ``resume_or_init`` at boot: it finds the latest
COMPLETE snapshot (manifest presence is the commit token), rebuilds the
trainer's full state onto whatever mesh the new job got — the same shape
("resumed") or a different one ("resharded", classified by comparing the
saved mesh + ``StepProgram`` fingerprint) — and rewinds the input feed to
the exact batch cursor. ``run`` then drives the training loop with an
interval snapshot policy and a SIGTERM/SIGINT ``PreemptionGuard``: on
preemption it finishes the in-flight step, drains the dispatch window,
forces a final synchronous snapshot, and returns cleanly — the relaunched
job loses zero completed steps and replays the trajectory exactly
(tests/test_elastic.py asserts K+1..K+10 loss/param parity).

The loop body keeps losses as ``PendingScalar`` handles (mxlint's
sync-in-loop pass hot-lists ``run`` — a ``float()`` on a step output in
here would re-serialize the device pipeline and fail CI).
"""
from __future__ import annotations

import signal
import threading
from typing import Any, Callable, Dict, Optional

from ..base import MXNetError
from .. import telemetry as _telem
from ..telemetry import tracing as _tracing
from . import manifest as _manifest
from . import state as _state
from .snapshot import SnapshotManager

__all__ = ["capture_trainer", "save_trainer", "resume_or_init",
           "PreemptionGuard", "run"]


def capture_trainer(trainer, feed=None) -> Dict[str, Any]:
    """Trainer snapshot (elastic/state.py schema), with the input feed's
    cursor folded into meta so restore rewinds the data stream too."""
    snap = _state.capture(trainer)
    if feed is not None and hasattr(feed, "state_dict"):
        snap["meta"]["feed"] = feed.state_dict()
    return snap


def save_trainer(manager: SnapshotManager, trainer, feed=None,
                 wait: bool = False):
    """Capture + async save at the trainer's current step."""
    manager.save(trainer._t, capture_trainer(trainer, feed), wait=wait)


def resume_or_init(directory: str, make_trainer: Callable[[], Any],
                   feed=None, max_to_keep: int = 3,
                   save_interval_steps: Optional[int] = None,
                   coordinator=None):
    """Boot a worker: restore the latest complete snapshot, or start fresh.

    ``make_trainer`` constructs the trainer for THIS job's mesh/config;
    restore reshards the saved state onto it. Returns ``(manager, trainer,
    start_step, outcome)`` with outcome one of ``"fresh"`` (no snapshot),
    ``"resumed"`` (same mesh + step program), ``"resharded"`` (state was
    re-laid-out for a different mesh or program). Booked on the
    ``mx_resume_total{outcome}`` counter.

    With a ``coordinator`` (elastic/coordinator.py) the manifest is
    additionally validated against the group epoch — fence token present,
    generation not from the future, on-disk ready markers consistent —
    and a snapshot written by a different world size classifies as
    ``"resharded"``."""
    mgr = SnapshotManager(directory, max_to_keep=max_to_keep,
                          save_interval_steps=save_interval_steps,
                          coordinator=coordinator)
    step = mgr.latest_step()
    trainer = make_trainer()
    if step is None:
        _record_resume("fresh")
        return mgr, trainer, 0, "fresh"
    man = _manifest.load(mgr.directory, step)
    if coordinator is not None:
        coordinator.validate_manifest(man, step)
    meta = man["meta"]
    with _manifest.SnapshotReader(mgr.directory, step, manifest=man) as rd:
        _state.install(trainer, meta, rd, rd.names)
    if feed is not None and meta.get("feed") is not None \
            and hasattr(feed, "load_state_dict"):
        feed.load_state_dict(meta["feed"])
    if hasattr(trainer, "mesh") and hasattr(trainer, "_program"):
        mesh_now = {str(a): int(s)
                    for a, s in dict(trainer.mesh.shape).items()}
        outcome = "resumed" if (mesh_now == meta.get("mesh")
                                and trainer._program.fingerprint
                                == meta.get("program")) else "resharded"
    elif coordinator is not None and meta.get("members"):
        # a coordinator-committed snapshot records the membership it was
        # partitioned over: restoring onto a different live set is a
        # re-layout even when the trainer has no mesh (the drill's toy
        # trainer)
        live = coordinator.view(bump=False).live
        outcome = "resumed" if sorted(meta["members"]) == live \
            else "resharded"
    else:
        outcome = "resumed"
    _record_resume(outcome)
    return mgr, trainer, int(meta["step"]), outcome


def _record_resume(outcome: str):
    if _telem._ENABLED:
        _telem.record_resume(outcome, source="elastic")
    from ..telemetry import goodput as _goodput
    if _goodput._ENABLED and outcome != "fresh":
        # boot-to-resume wall time is the run's restart downtime: booked
        # run-level (ring + totals), never folded into one step's
        # waterfall. Anchored at goodput's module import — the earliest
        # process stamp available without patching the interpreter.
        _goodput.record_restart_downtime(outcome)


class PreemptionGuard:
    """Convert SIGTERM/SIGINT into a cooperative flag the train loop polls.

    The handler only sets an event — no I/O, no raising into arbitrary
    frames — so the in-flight step completes and the loop exits at a step
    boundary where a consistent snapshot is possible. Restores the prior
    handlers on ``__exit__``. Outside the main thread (where Python
    forbids signal handlers) it degrades to an inert flag that can still
    be set programmatically via ``request_stop``."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self._flag = threading.Event()
        self._prev: Dict[int, Any] = {}

    def _handle(self, signum, frame):
        self._flag.set()

    def request_stop(self):
        self._flag.set()

    @property
    def triggered(self) -> bool:
        return self._flag.is_set()

    def __enter__(self):
        for s in self.signals:
            try:
                self._prev[s] = signal.signal(s, self._handle)
            except ValueError:  # not the main thread
                pass
        return self

    def __exit__(self, *exc):
        for s, h in self._prev.items():
            signal.signal(s, h)
        self._prev.clear()
        return False


def _xy(batch):
    data = getattr(batch, "data", None)
    if data is not None:  # io.DataBatch
        label = getattr(batch, "label", None)
        return data[0], (label[0] if label else None)
    x, y = batch
    return x, y


def run(trainer, feed, num_steps: int, directory: Optional[str] = None,
        manager: Optional[SnapshotManager] = None,
        save_every: Optional[int] = None, guard: Optional[PreemptionGuard]
        = None, on_step=None, coordinator=None) -> Dict[str, Any]:
    """Drive ``trainer.step`` over ``feed`` until ``num_steps`` TOTAL steps
    (the trainer's step counter, so a resumed trainer does only the
    remainder), snapshotting every ``save_every`` steps and on exit.

    ``feed`` yields ``(x, y)`` tuples or ``DataBatch`` items; epoch ends
    trigger ``feed.reset()``. On SIGTERM/SIGINT the loop finishes the
    current step, drains the dispatch window, writes a final synchronous
    snapshot, and returns ``{"preempted": True}`` — relaunching the job
    through ``resume_or_init`` continues the exact trajectory. Losses are
    returned as unsynced ``PendingScalar`` handles.

    With a ``coordinator`` the loop participates in the COORDINATED stop
    protocol (docs/reliability.md): every step boundary refreshes the
    membership heartbeat and polls for a stop intent (this host's own
    preemption posts one); once a stop is posted, every live host acks
    its current step, the stop resolves to ``S = max(acked steps)``,
    hosts behind S run exactly up to S, and ALL survivors write their
    final snapshot at the same step. The drain is guarded by the hang
    watchdog, and the final cross-host snapshot retries under a
    refreshed membership view when a straggler abort or a dead peer
    interrupts the two-phase commit.
    """
    if manager is None:
        if directory is None:
            raise MXNetError("elastic.run needs directory= or manager=")
        manager = SnapshotManager(directory,
                                  save_interval_steps=save_every,
                                  coordinator=coordinator)
    else:
        if save_every is not None:
            manager.save_interval_steps = int(save_every)
        if coordinator is not None and manager.coordinator is None:
            manager.coordinator = coordinator
    losses = []
    preempted = False
    stop_info = None
    own_guard = guard is None
    g = PreemptionGuard() if own_guard else guard
    if own_guard:
        g.__enter__()
    try:
        it = iter(feed)
        while trainer._t < num_steps:
            if g.triggered:
                preempted = True
                if coordinator is not None and stop_info is None:
                    # tell the peers: everyone converges on one final S
                    stop_info = coordinator.post_stop(trainer._t,
                                                      reason="preempted")
                if _tracing._ENABLED:
                    # black-box dump at the preemption boundary: the final
                    # steps' spans survive even if the relaunch clobbers
                    # everything else
                    _tracing.event("mx.preemption", step=trainer._t)
                    _tracing.dump_flight_recorder(reason="preemption")
                break
            if coordinator is not None:
                stop_info = coordinator.step_poll(trainer._t)
                if stop_info is not None:
                    preempted = True
                    break
            try:
                batch = next(it)
            except StopIteration:
                if not hasattr(feed, "reset"):
                    break
                feed.reset()
                it = iter(feed)
                continue
            x, y = _xy(batch)
            try:
                losses.append(trainer.step(x, y))
            except BaseException:  # dump-and-reraise: nothing is swallowed  # mxlint: disable=broad-except
                # unhandled-step-exception hook: dump the recorder before
                # the error unwinds past the loop (callers often catch and
                # relaunch, so sys.excepthook would never see it)
                if _tracing._ENABLED:
                    _tracing.dump_flight_recorder(reason="step_exception")
                raise
            if manager.should_save(trainer._t):
                try:
                    save_trainer(manager, trainer, feed)
                except MXNetError as e:
                    # a failed INTERVAL snapshot (exhausted IO retries on a
                    # flaky filesystem) must not kill a healthy training
                    # job: resume falls back to the previous complete
                    # snapshot. The FINAL snapshot below stays strict.
                    import warnings
                    warnings.warn(
                        f"elastic.run: interval snapshot at step "
                        f"{trainer._t} failed and was skipped ({e}); "
                        "training continues, resume falls back to the "
                        "previous snapshot", RuntimeWarning)
                    if _telem._ENABLED:
                        _telem.counter(
                            "mx_snapshot_failures_total",
                            "Interval snapshots skipped after exhausting "
                            "IO retries", ("source",)) \
                            .labels("elastic").inc()
            if on_step is not None:
                on_step(trainer._t, losses[-1])
        if coordinator is not None and preempted:
            # phase-1 quiesce: every live host acks its step; the stop
            # resolves to S = max over acks, and a host behind S levels
            # up — every survivor's final snapshot is at the SAME step
            target = min(coordinator.resolve_stop(trainer._t), num_steps)
            while trainer._t < target:
                try:
                    batch = next(it)
                except StopIteration:
                    if not hasattr(feed, "reset"):
                        break
                    feed.reset()
                    it = iter(feed)
                    continue
                x, y = _xy(batch)
                losses.append(trainer.step(x, y))
        # exit (normal or preempted): drain in-flight steps, then one
        # final synchronous snapshot so the relaunch loses nothing
        if coordinator is not None:
            with coordinator.watchdog("drain"):
                trainer.drain()
        else:
            trainer.drain()
        if trainer._t != manager._last_saved:
            _final_save(manager, trainer, feed, coordinator)
        else:
            manager.wait_until_finished()
    finally:
        if own_guard:
            g.__exit__(None, None, None)
    return {"step": trainer._t, "losses": losses, "preempted": preempted,
            "stop": stop_info}


def _final_save(manager, trainer, feed, coordinator, attempts: int = 3):
    """The strict final snapshot. Single-host: one shot, failures
    surface. Coordinated: a straggler abort or a peer dying mid-commit
    fails the whole two-phase barrier for every survivor — each retries
    under the REFRESHED membership view (new generation, re-partitioned
    ownership, fresh markers), bounded by ``attempts``."""
    if coordinator is None:
        save_trainer(manager, trainer, feed, wait=True)
        return
    for attempt in range(int(attempts)):
        try:
            save_trainer(manager, trainer, feed, wait=True)
            return
        except MXNetError:
            if attempt == int(attempts) - 1:
                raise
            coordinator.heartbeat(trainer._t, force=True)
            coordinator.view()      # refresh epoch before re-partitioning
