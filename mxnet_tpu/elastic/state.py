"""Trainer state capture/install for elastic snapshots.

``capture(trainer)`` produces ``{"leaves": {name: array}, "meta": {...}}``
— the schema ``SnapshotManager`` persists. Leaves stay DEVICE arrays with
their live shardings (the snapshot writer copies and chunks them off the
step path); meta is host-side JSON: step counter, optimizer schedule
(``num_update`` / ``begin_num_update`` / per-index update counts /
lr-scheduler fields), fp16 loss-scaler state, the ZeRO bucket plans, mesh
shape, and the ``StepProgram`` fingerprint (restore uses it to classify
the boot as "resumed" vs "resharded").

``install(trainer, meta, fetch, names)`` is the inverse: ``fetch(name)``
returns the GLOBAL host array for a leaf (a ``manifest.SnapshotReader``,
or a plain dict lookup for in-memory ``load_state_dict``). Placement goes
through ``jax.make_array_from_callback`` against the NEW trainer's
template shardings, so the same path restores onto the saving mesh or a
different one.

Resharding rules (docs/checkpointing.md):

  - parameters and replicated optimizer state are mesh-independent
    (global shapes) — they restore onto any mesh;
  - ZeRO bucket state is layout-dependent (``padded_size`` is a multiple
    of the dp degree): cross-dp restore re-canonicalizes — each saved
    bucket's flat lanes are split back into per-parameter segments using
    the SAVED ``BucketSpec`` (recorded in the manifest) and re-packed
    under the NEW trainer's plan, zero-padded to its shard multiple;
  - pipeline stage stacks reorder rows when the (pp, virtual_stages)
    schedule changes (``_stack_order`` permutation); ZeRO-over-pp state
    cannot cross pp degrees (per-stage shards have no global layout) and
    restore raises an informative error instead of mis-assembling.

Leaf naming (flat, positional within each structural slot — gluon
parameter NAMES embed process-global counters and never match across
restarts, the same reason checkpoint.py keys positionally):

    dp:  param.{i}            opt.p{i}.{k}   (replicated update)
         opt.b{j}.{k} opt.x{i}.{k}           (zero buckets / extras)
    pp:  param.e.{i} param.s.{i} param.h.{i}
         opt.e.{i}.{k} opt.s.{i}.{k} opt.h.{i}.{k}
         opt.ze.{j}.{k} opt.zs.{j}.{k} opt.zh.{j}.{k}
    both: rng                 (raw uint32 key data, a device leaf)
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Set

import numpy as _np

from ..base import MXNetError

__all__ = ["capture", "install", "sched_state", "install_sched"]


# ---------------------------------------------------------------------------
# Optimizer schedule state (satellite: lr schedule / step-counter parity)
# ---------------------------------------------------------------------------

def sched_state(opt) -> Dict[str, Any]:
    """Host-side schedule counters a resumed run needs for lr parity at
    step K+1: ``num_update``/``begin_num_update``, the per-index update
    counts, and the lr-scheduler's mutable fields (FactorScheduler.count,
    MultiFactorScheduler.cur_step_ind, decayed base_lr)."""
    d = {"num_update": int(opt.num_update),
         "begin_num_update": int(opt.begin_num_update),
         "index_update_count": {str(k): int(v)
                                for k, v in opt._index_update_count.items()},
         "scheduler": None}
    sched = getattr(opt, "lr_scheduler", None)
    if sched is not None:
        d["scheduler"] = sched.state_dict()
    return d


def install_sched(opt, d: Dict[str, Any]):
    opt.num_update = int(d["num_update"])
    opt.begin_num_update = int(d.get("begin_num_update", 0))
    counts = {}
    for k, v in (d.get("index_update_count") or {}).items():
        try:
            k = int(k)
        except (TypeError, ValueError):
            pass
        counts[k] = int(v)
    opt._index_update_count = counts
    sched = getattr(opt, "lr_scheduler", None)
    if sched is not None and d.get("scheduler") is not None:
        sched.load_state_dict(d["scheduler"])


# ---------------------------------------------------------------------------
# Shared helpers
# ---------------------------------------------------------------------------

def _bucket_dict(b) -> Dict[str, Any]:
    return {"dtype": b.dtype, "indices": list(b.indices),
            "offsets": list(b.offsets), "sizes": list(b.sizes),
            "shapes": [list(s) for s in b.shapes],
            "padded_size": b.padded_size, "ndp": b.ndp}


def _bucket_from(d) -> "Any":
    from ..parallel.zero import BucketSpec
    return BucketSpec(dtype=d["dtype"], indices=tuple(d["indices"]),
                      offsets=tuple(d["offsets"]), sizes=tuple(d["sizes"]),
                      shapes=tuple(tuple(s) for s in d["shapes"]),
                      padded_size=int(d["padded_size"]), ndp=int(d["ndp"]))


def _tree_leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)


def _tree_rebuild(template, leaves):
    import jax
    _, treedef = jax.tree_util.tree_flatten(template)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _place_like(host, like, what: str):
    """Place an assembled global host array under a template leaf's
    sharding (works on any mesh, single- or multi-process — the callback
    serves arbitrary index regions from the full host value)."""
    import jax
    host = _np.asarray(host)
    if not isinstance(like, jax.Array):
        return host
    if tuple(host.shape) != tuple(like.shape):
        raise MXNetError(
            f"snapshot leaf {what!r}: saved shape {tuple(host.shape)} != "
            f"trainer shape {tuple(like.shape)} — architecture mismatch")
    if _np.dtype(host.dtype) != _np.dtype(like.dtype):
        host = host.astype(like.dtype)
    return jax.make_array_from_callback(
        host.shape, like.sharding, lambda idx: host[idx])


def _fetch_np(fetch, name):
    try:
        return _np.asarray(fetch(name))
    except KeyError:
        raise MXNetError(
            f"snapshot is missing leaf {name!r} — saved with a different "
            "trainer configuration (optimizer/zero/precision)") from None


def _revector(old_specs, old_flats, new_spec) -> _np.ndarray:
    """Re-pack ONE flat state lane from the saved bucket layout onto a new
    bucket's layout: split each old flat vector back into per-parameter
    segments (saved offsets/sizes), then concatenate the new bucket's
    members in ITS order and zero-pad to its ``padded_size``."""
    pieces: Dict[int, _np.ndarray] = {}
    for spec, flat in zip(old_specs, old_flats):
        flat = _np.asarray(flat).reshape(-1)
        for i, o, s in zip(spec.indices, spec.offsets, spec.sizes):
            pieces[i] = flat[o:o + s]
    try:
        parts = [pieces[i] for i in new_spec.indices]
    except KeyError as e:
        raise MXNetError(
            f"zero-state reshard: parameter slot {e} absent from the saved "
            "bucket plan — trainable set changed between save and resume")
    out = _np.zeros((new_spec.padded_size,), parts[0].dtype)
    off = 0
    for p in parts:
        out[off:off + p.size] = p
        off += p.size
    return out


def _bucket_lane_count(names: Set[str], prefix: str) -> int:
    """How many ``{prefix}.{k}`` leaves the snapshot holds."""
    n = 0
    while f"{prefix}.{n}" in names:
        n += 1
    return n


def _restore_zero_carry(prefix_fmt, old_specs, new_specs, template_carry,
                        fetch, names, row_dim: Optional[int] = None):
    """Rebuild a tuple of per-bucket ``(wd, state...)`` carries.

    ``prefix_fmt`` formats the saved leaf prefix for old bucket ``j``
    (e.g. ``"opt.b{j}"``). When old and new specs agree the lanes restore
    verbatim; otherwise every flat lane is re-packed via ``_revector``.
    ``row_dim`` handles the pipeline stage buckets whose state leaves are
    (n_stages, padded) stacks — each row re-packs independently."""
    same = len(old_specs) == len(new_specs) and all(
        o.padded_size == n.padded_size and o.indices == n.indices
        and o.ndp == n.ndp for o, n in zip(old_specs, new_specs))
    # every old bucket's flat lanes, fetched host-side once
    old_lanes: List[List[_np.ndarray]] = []
    for j in range(len(old_specs)):
        prefix = prefix_fmt.format(j=j)
        k = _bucket_lane_count(names, prefix)
        old_lanes.append([_fetch_np(fetch, f"{prefix}.{k_}")
                          for k_ in range(k)])
    carry = []
    for j2, (new_spec, tmpl) in enumerate(zip(new_specs, template_carry)):
        tmpl_leaves = _tree_leaves(tmpl)
        if same:
            lanes = old_lanes[j2]
            if len(lanes) != len(tmpl_leaves):
                raise MXNetError(
                    "zero-state restore: saved bucket has "
                    f"{len(lanes)} state lanes, trainer expects "
                    f"{len(tmpl_leaves)} — optimizer mismatch")
            new_leaves = [_place_like(h, t, f"zero bucket {j2} lane {k}")
                          for k, (h, t) in enumerate(zip(lanes, tmpl_leaves))]
            carry.append(_tree_rebuild(tmpl, new_leaves))
            continue
        # cross-layout: scalar lanes come from the old bucket holding this
        # bucket's first parameter; flat lanes re-pack per parameter
        first = new_spec.indices[0]
        j_scalar = next((jo for jo, s in enumerate(old_specs)
                         if first in s.indices), 0)
        new_leaves = []
        for k, t in enumerate(tmpl_leaves):
            shape = tuple(t.shape)
            if shape == ():
                new_leaves.append(_place_like(
                    old_lanes[j_scalar][k], t, f"zero scalar lane {k}"))
            elif row_dim is not None and len(shape) == 2:
                rows = [_revector(old_specs,
                                  [lane[k][r] for lane in (old_lanes[jo]
                                   for jo in range(len(old_specs)))]
                                  if False else
                                  [old_lanes[jo][k][r]
                                   for jo in range(len(old_specs))],
                                  new_spec)
                        for r in range(shape[0])]
                new_leaves.append(_place_like(
                    _np.stack(rows), t, f"zero stage lane {k}"))
            else:
                new_leaves.append(_place_like(
                    _revector(old_specs,
                              [old_lanes[jo][k]
                               for jo in range(len(old_specs))],
                              new_spec),
                    t, f"zero flat lane {k}"))
        carry.append(_tree_rebuild(tmpl, new_leaves))
    return tuple(carry)


# ---------------------------------------------------------------------------
# Capture
# ---------------------------------------------------------------------------

def capture(trainer) -> Dict[str, Any]:
    """Snapshot-schema view of a trainer's full training state. Pure
    bookkeeping on the caller's thread: leaves reference the live device
    arrays (SnapshotManager copies them), meta reads host counters only —
    no device transfer, no sync (mxlint host-sync hot list)."""
    if hasattr(trainer, "elastic_state"):
        # duck-typed extension point: a trainer that is neither of the
        # fused pair (the multi-host drill's toy trainer, user trainers)
        # supplies its own snapshot-schema dict + elastic_install()
        return trainer.elastic_state()
    if hasattr(trainer, "_params_raw"):
        return _capture_dp(trainer)
    if hasattr(trainer, "_s_raw"):
        return _capture_pp(trainer)
    raise MXNetError(f"cannot snapshot {type(trainer).__name__}; expected "
                     "DataParallelTrainer, PipelineTrainer, or an "
                     "elastic_state()/elastic_install() provider")


def _common_meta(trainer) -> Dict[str, Any]:
    from .. import random as _rng
    meta = {
        "format": 1,
        "step": trainer._t,
        "optimizer": type(trainer.optimizer).__name__,
        "mesh": {str(a): s for a, s in dict(trainer.mesh.shape).items()},
        "program": trainer._program.fingerprint,
        "sched": sched_state(trainer.optimizer),
        "scaler": None,
    }
    scaler = getattr(trainer, "_scaler", None)
    if scaler is not None:
        meta["scaler"] = scaler.state_dict()
    return meta


def _capture_dp(trainer) -> Dict[str, Any]:
    from .. import random as _rng
    leaves: Dict[str, Any] = {}
    for i, w in enumerate(trainer._params_raw):
        leaves[f"param.{i}"] = w
    if trainer._zero:
        carry, extra = trainer._opt_state
        for j, c in enumerate(carry):
            for k, leaf in enumerate(_tree_leaves(c)):
                leaves[f"opt.b{j}.{k}"] = leaf
        for i, st in enumerate(extra):
            for k, leaf in enumerate(_tree_leaves(st)):
                leaves[f"opt.x{i}.{k}"] = leaf
    else:
        for i, st in enumerate(trainer._opt_state):
            for k, leaf in enumerate(_tree_leaves(st)):
                leaves[f"opt.p{i}.{k}"] = leaf
    leaves["rng"] = _rng.get_state_raw()
    meta = _common_meta(trainer)
    meta.update({
        "kind": "dp",
        "n_params": len(trainer._params_raw),
        "zero": trainer._zero,
        "dp_degree": trainer._dp_degree,
        "zero_plan": [_bucket_dict(b) for b in trainer._zero_plan],
    })
    return {"leaves": leaves, "meta": meta}


def _capture_pp(trainer) -> Dict[str, Any]:
    from .. import random as _rng
    leaves: Dict[str, Any] = {}
    for tag, group in (("e", trainer._e_raw), ("s", trainer._s_raw),
                       ("h", trainer._h_raw)):
        for i, w in enumerate(group):
            leaves[f"param.{tag}.{i}"] = w
    if trainer._zero:
        for tag, carry in (("ze", trainer._opt_e), ("zs", trainer._opt_s),
                           ("zh", trainer._opt_h)):
            for j, c in enumerate(carry):
                for k, leaf in enumerate(_tree_leaves(c)):
                    leaves[f"opt.{tag}.{j}.{k}"] = leaf
    else:
        for tag, grp in (("e", trainer._opt_e), ("s", trainer._opt_s),
                         ("h", trainer._opt_h)):
            for i, st in enumerate(grp):
                for k, leaf in enumerate(_tree_leaves(st)):
                    leaves[f"opt.{tag}.{i}.{k}"] = leaf
    leaves["rng"] = _rng.get_state_raw()
    meta = _common_meta(trainer)
    meta.update({
        "kind": "pp",
        "n_e": len(trainer._e_raw), "n_s": len(trainer._s_raw),
        "n_h": len(trainer._h_raw),
        "n_layers": trainer.n_layers,
        "n_stages": trainer.n_stages,
        "virtual_stages": trainer.virtual_stages,
        "stack_order": list(trainer._stack_order),
        "zero": trainer._zero,
        "dp_degree": trainer.n_dp,
        # partitioned tp stores view-shaped GLOBALS (tp-degree-independent:
        # a tp=2 snapshot restores onto a tp=4 trainer), but the leaf
        # SHAPES differ from the sharded/no-tp layout — kind-checked on
        # install. ZeRO carries are per-tp-rank and pin the degree.
        "tp_mode": getattr(trainer, "tp_mode", "sharded"),
        "tp_degree": trainer.n_tp,
        "sequence_parallel": getattr(trainer, "sequence_parallel", False),
    })
    if trainer._zero:
        meta["zero_plan_e"] = [_bucket_dict(b) for b in trainer._zplan_e]
        meta["zero_plan_s"] = [_bucket_dict(b) for b in trainer._zplan_s]
        meta["zero_plan_h"] = [_bucket_dict(b) for b in trainer._zplan_h]
    return {"leaves": leaves, "meta": meta}


# ---------------------------------------------------------------------------
# Install
# ---------------------------------------------------------------------------

def install(trainer, meta: Dict[str, Any], fetch: Callable[[str], Any],
            names: Set[str]):
    """Install a snapshot into a freshly-constructed trainer. ``fetch``
    returns the global host (or device) value for a leaf name; ``names``
    is the set of leaf names the snapshot holds."""
    kind = meta.get("kind")
    if kind not in ("dp", "pp") and hasattr(trainer, "elastic_install"):
        # the duck-typed counterpart of capture()'s elastic_state() hook:
        # the trainer owns its own leaf layout and host-state restore
        # (including its step counter), so the fused-pair install below
        # — and its trainer.sync() — does not apply
        trainer.elastic_install(meta, fetch, names)
        return trainer
    if kind == "dp":
        if not hasattr(trainer, "_params_raw"):
            raise MXNetError("snapshot holds DataParallelTrainer state but "
                             f"the target is {type(trainer).__name__}")
        _install_dp(trainer, meta, fetch, names)
    elif kind == "pp":
        if not hasattr(trainer, "_s_raw"):
            raise MXNetError("snapshot holds PipelineTrainer state but "
                             f"the target is {type(trainer).__name__}")
        _install_pp(trainer, meta, fetch, names)
    else:
        raise MXNetError(f"unknown snapshot kind {kind!r}")
    _install_host_state(trainer, meta, fetch, names)
    trainer.sync()
    return trainer


def _check(cond, msg):
    if not cond:
        raise MXNetError(msg)


def _install_host_state(trainer, meta, fetch, names):
    from .. import random as _rng
    trainer._t = int(meta["step"])
    if meta.get("sched"):
        install_sched(trainer.optimizer, meta["sched"])
    else:
        trainer.optimizer.num_update = trainer._t
    scaler = getattr(trainer, "_scaler", None)
    if scaler is not None and meta.get("scaler"):
        scaler.load_state_dict(meta["scaler"])
    if "rng" in names:
        _rng.set_state_raw(_fetch_np(fetch, "rng"))
    # drop the device-resident per-call caches run_steps keeps (stale lr /
    # step-counter / RNG uploads would otherwise survive the restore)
    for attr in ("_t_dev_val", "_lr_cache_sig", "_scale_cache_val",
                 "_key_dev"):
        if hasattr(trainer, attr):
            setattr(trainer, attr, None)


def _install_dp(trainer, meta, fetch, names):
    _check(meta.get("optimizer") == type(trainer.optimizer).__name__,
           f"snapshot optimizer {meta.get('optimizer')!r} != trainer "
           f"{type(trainer.optimizer).__name__!r}")
    n = len(trainer._params_raw)
    _check(int(meta.get("n_params", -1)) == n,
           f"snapshot has {meta.get('n_params')} parameters, trainer has "
           f"{n} — architecture mismatch")
    _check(bool(meta.get("zero")) == bool(trainer._zero),
           "snapshot and trainer disagree on zero_update; construct the "
           "resuming trainer with the same zero_update setting")
    # parameters: global shapes are mesh-independent — any-mesh restore
    trainer._params_raw = [
        _place_like(_fetch_np(fetch, f"param.{i}"), w, f"param.{i}")
        for i, w in enumerate(trainer._params_raw)]
    if trainer._zero:
        carry, extra = trainer._opt_state
        old_specs = [_bucket_from(d) for d in meta.get("zero_plan", [])]
        new_carry = _restore_zero_carry(
            "opt.b{j}", old_specs, list(trainer._zero_plan), list(carry),
            fetch, names)
        new_extra = []
        for i, st in enumerate(extra):
            tmpl_leaves = _tree_leaves(st)
            new_extra.append(_tree_rebuild(st, [
                _place_like(_fetch_np(fetch, f"opt.x{i}.{k}"), t,
                            f"opt.x{i}.{k}")
                for k, t in enumerate(tmpl_leaves)]))
        trainer._opt_state = (new_carry, tuple(new_extra))
    else:
        new_state = []
        for i, st in enumerate(trainer._opt_state):
            tmpl_leaves = _tree_leaves(st)
            new_state.append(_tree_rebuild(st, [
                _place_like(_fetch_np(fetch, f"opt.p{i}.{k}"), t,
                            f"opt.p{i}.{k}")
                for k, t in enumerate(tmpl_leaves)]))
        trainer._opt_state = new_state


def _stack_perm(old_order: Sequence[int], new_order: Sequence[int]):
    """Row permutation mapping a stacked cell leaf saved under
    ``old_order`` onto ``new_order``: new row k' holds global layer
    ``new_order[k']``, which the save put at row
    ``old_order.index(new_order[k'])``."""
    if list(old_order) == list(new_order):
        return None
    pos = {m: r for r, m in enumerate(old_order)}
    try:
        return [pos[m] for m in new_order]
    except KeyError:
        raise MXNetError(
            "snapshot and trainer stack orders cover different layer sets "
            f"({sorted(pos)} vs {sorted(new_order)})")


def _install_pp(trainer, meta, fetch, names):
    _check(meta.get("optimizer") == type(trainer.optimizer).__name__,
           f"snapshot optimizer {meta.get('optimizer')!r} != trainer "
           f"{type(trainer.optimizer).__name__!r}")
    for key, have in (("n_e", len(trainer._e_raw)),
                      ("n_s", len(trainer._s_raw)),
                      ("n_h", len(trainer._h_raw)),
                      ("n_layers", trainer.n_layers)):
        _check(int(meta.get(key, -1)) == int(have),
               f"snapshot {key}={meta.get(key)} != trainer {have} — "
               "architecture mismatch")
    _check(bool(meta.get("zero")) == bool(trainer._zero),
           "snapshot and trainer disagree on zero_update; construct the "
           "resuming trainer with the same zero_update setting")
    saved_mode = meta.get("tp_mode", "sharded")
    have_mode = getattr(trainer, "tp_mode", "sharded")
    _check(saved_mode == have_mode,
           f"snapshot was taken under tp_mode={saved_mode!r} but the "
           f"resuming trainer uses tp_mode={have_mode!r}; partitioned "
           "snapshots store blocked view-shaped leaves that only a "
           "partitioned trainer can install (and vice versa)")
    old_order = meta.get("stack_order") or list(range(trainer.n_layers))
    perm = _stack_perm(old_order, trainer._stack_order)
    same_pp = (int(meta.get("n_stages", -1)) == trainer.n_stages
               and int(meta.get("virtual_stages", 1)) ==
               trainer.virtual_stages)

    def _rows(host, tmpl):
        if perm is not None and getattr(host, "ndim", 0) >= 1 \
                and host.shape[0] == trainer.n_layers:
            host = host[perm]
        return host

    trainer._e_raw = [
        _place_like(_fetch_np(fetch, f"param.e.{i}"), w, f"param.e.{i}")
        for i, w in enumerate(trainer._e_raw)]
    trainer._h_raw = [
        _place_like(_fetch_np(fetch, f"param.h.{i}"), w, f"param.h.{i}")
        for i, w in enumerate(trainer._h_raw)]
    trainer._s_raw = [
        _place_like(_rows(_fetch_np(fetch, f"param.s.{i}"), w), w,
                    f"param.s.{i}")
        for i, w in enumerate(trainer._s_raw)]
    if trainer._zero:
        _check(same_pp and perm is None,
               "ZeRO-over-pp optimizer state cannot reshard across pipeline "
               f"degrees (saved pp={meta.get('n_stages')}x"
               f"v{meta.get('virtual_stages')}, trainer pp="
               f"{trainer.n_stages}xv{trainer.virtual_stages}); resume on "
               "the saved pipeline layout, or save without zero_update")
        _check(int(meta.get("tp_degree", 1)) == trainer.n_tp,
               "ZeRO optimizer state under partitioned tp is laid out per "
               f"tp rank and cannot reshard across tp degrees (saved "
               f"tp={meta.get('tp_degree', 1)}, trainer tp={trainer.n_tp}); "
               "resume on the saved tp degree, or save without zero_update")
        olds = {t: [_bucket_from(d) for d in meta.get(f"zero_plan_{t}", [])]
                for t in ("e", "s", "h")}
        trainer._opt_e = _restore_zero_carry(
            "opt.ze.{j}", olds["e"], list(trainer._zplan_e),
            list(trainer._opt_e), fetch, names)
        trainer._opt_h = _restore_zero_carry(
            "opt.zh.{j}", olds["h"], list(trainer._zplan_h),
            list(trainer._opt_h), fetch, names)
        trainer._opt_s = _restore_zero_carry(
            "opt.zs.{j}", olds["s"], list(trainer._zplan_s),
            list(trainer._opt_s), fetch, names, row_dim=0)
    else:
        def _grp(tag, group, permute):
            out = []
            for i, st in enumerate(group):
                tmpl_leaves = _tree_leaves(st)
                leaves = []
                for k, t in enumerate(tmpl_leaves):
                    host = _fetch_np(fetch, f"opt.{tag}.{i}.{k}")
                    if permute:
                        host = _rows(host, t)
                    leaves.append(_place_like(host, t, f"opt.{tag}.{i}.{k}"))
                out.append(_tree_rebuild(st, leaves))
            return out
        trainer._opt_e = _grp("e", trainer._opt_e, False)
        trainer._opt_h = _grp("h", trainer._opt_h, False)
        trainer._opt_s = _grp("s", trainer._opt_s, True)
