"""Async sharded snapshots: no gather, no host sync on the step path.

``SnapshotManager.save`` is designed to sit INSIDE a training loop between
``step()`` dispatches, so it must never serialize the device pipeline:

  1. **Donation safety without blocking**: the fused trainers donate their
     param/optimizer buffers to the next step's jit — a snapshot holding
     references to the live arrays would read deleted buffers as soon as
     the next step dispatches. ``save`` therefore dispatches one eager
     ``jnp.copy`` per leaf: an async device-side copy that lands in fresh,
     undonated buffers with the SAME sharding, queued behind whatever step
     is in flight. No host transfer happens on the caller's thread.
  2. **Background write**: a writer thread blocks on the copies (that wait
     overlaps the next steps' compute — the ``DispatchWindow`` slack),
     pulls only the chunks this process owns (addressable shards with
     ``replica_id == 0`` — each ZeRO shard leaves the host it lives on,
     exactly once, never gathered), writes ``shard-<p>.npz``, and commits
     the manifest atomically (elastic/manifest.py).
  3. **Bounded memory**: at most one snapshot is in flight; a new ``save``
     first joins the previous writer, so the copy working set never
     exceeds one model+optimizer footprint.

The writer books ``mx_checkpoint_save_seconds`` / ``mx_checkpoint_bytes_
total`` on commit (tools/check_instrumentation.py gates this), and the
save/copy entry points are on mxlint's host-sync hot list: a ``float()``
or ``np.asarray`` creeping into them fails CI, so the snapshot path can
never silently start blocking the jitted step.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, Optional

from ..base import MXNetError, env
from .. import telemetry as _telem
from ..telemetry import tracing as _tracing
from . import manifest as _manifest

__all__ = ["SnapshotManager"]


def _is_jax_array(v) -> bool:
    """jax.Array check that never IMPORTS jax: a pure-host coordinator
    participant (the drill's toy trainer) must not pay backend init just
    to snapshot numpy leaves."""
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(v, jax.Array)

env.declare("MXNET_TPU_SNAPSHOT_EVERY", 0, int,
            "Default SnapshotManager save interval in steps (0 = only "
            "explicit/forced saves); elastic.run() consults should_save")


class SnapshotManager:
    """Step-indexed async sharded snapshots with retention + atomicity.

    ``save(step, snapshot)`` takes the dict a trainer's ``state_dict()``
    (elastic/state.py ``capture``) produces: ``{"leaves": {name: array},
    "meta": {...}}``. Leaves may be jax arrays (device, any sharding) or
    host values; meta must be JSON-serializable.

    With a ``coordinator`` (elastic/coordinator.py) the manager becomes
    one participant in the TWO-PHASE cross-host commit: this host writes
    only its owned chunks plus a ready marker, and whoever the group
    view elects leader assembles the generation-stamped global manifest
    once every live member's marker landed (docs/checkpointing.md,
    "Multi-host snapshots").
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: Optional[int] = None,
                 coordinator=None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = int(max_to_keep)
        self.save_interval_steps = int(
            env.get("MXNET_TPU_SNAPSHOT_EVERY")
            if save_interval_steps is None else save_interval_steps)
        self.coordinator = coordinator
        self._writer: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._last_saved: Optional[int] = None
        self.save_seconds = 0.0
        self.bytes_written = 0

    # -- policy --------------------------------------------------------------
    def should_save(self, step) -> bool:
        """Interval policy for the supervised loop: save every
        ``save_interval_steps`` steps, never the same step twice."""
        k = self.save_interval_steps
        return k > 0 and step > 0 and step % k == 0 \
            and step != self._last_saved

    # -- hot path ------------------------------------------------------------
    def save(self, step, snapshot: Dict[str, Any], wait: bool = False):
        """Snapshot asynchronously; returns after dispatching device-side
        copies (no host transfer on this thread unless ``wait=True``)."""
        self.wait_until_finished()  # one in flight: bounded copy memory
        leaves = snapshot["leaves"]
        meta = dict(snapshot.get("meta") or {})
        meta.setdefault("step", step)
        copies = self._copy_leaves(leaves)
        self._last_saved = step
        t0 = time.perf_counter()
        # snapshot spans parent to the caller's trace (the training loop's
        # step span when armed there), carried explicitly across the
        # writer-thread boundary
        ctx = (_tracing.current() or _tracing.new_root("snapshot")) \
            if _tracing._ENABLED else None
        self._writer = threading.Thread(
            target=self._write, args=(step, copies, meta, t0, ctx),
            daemon=True, name=f"mx-snapshot-{step}")
        self._writer.start()
        if wait:
            self.wait_until_finished()

    @staticmethod
    def _copy_leaves(leaves: Dict[str, Any]) -> Dict[str, Any]:
        """Per-leaf eager device copies. One jit over all leaves would
        reject mixed committed placements (mesh-sharded state + the
        default-device RNG leaf); per-leaf ``jnp.copy`` dispatches each
        copy on its own devices, async, sharding-preserving. Host ndarray
        leaves are copied too — an in-place optimizer (the drill's toy
        trainer, host-side scheduler state) keeps mutating the live
        buffer while the background writer serializes the copy."""
        import numpy as _np
        out = {}
        for name, v in leaves.items():
            if _is_jax_array(v):
                import jax.numpy as jnp
                out[name] = jnp.copy(v)
            elif isinstance(v, _np.ndarray):
                out[name] = _np.array(v)
            else:
                out[name] = v
        return out

    # -- background writer ---------------------------------------------------
    def _write(self, step, copies, meta, t0, ctx=None):
        try:
            if ctx is not None and _tracing._ENABLED:
                with _tracing.attach(ctx), \
                        _tracing.span("mx.elastic.snapshot_write", step=step):
                    nbytes, sdir, proc = self._write_entries(step, copies)
            else:
                nbytes, sdir, proc = self._write_entries(step, copies)
            if self.coordinator is not None:
                self._commit_coordinated(sdir, step, meta, nbytes, t0, ctx)
            elif proc == 0:
                self._commit(sdir, step, meta, nbytes, t0, ctx)
        except BaseException as e:  # stash-and-reraise thread boundary: surfaced at the next save()/wait  # mxlint: disable=broad-except
            self._error = e

    def _write_entries(self, step, copies):
        sdir = _manifest.step_path(self.directory, step)
        os.makedirs(sdir, exist_ok=True)
        import numpy as _np
        coord = self.coordinator
        partition = coord is not None and coord.partition_ownership
        if coord is not None:
            # the control plane is the authority on this host's identity
            # — a pure-host (drill) participant never touches the jax
            # distributed runtime
            proc = coord.rank
        else:
            import jax
            proc = jax.process_index()
        entries = []
        for name, v in copies.items():
            if _is_jax_array(v) and not partition:
                for shard in v.addressable_shards:
                    if shard.replica_id != 0:
                        continue
                    index = [sl.indices(dim)[:2]
                             for sl, dim in zip(shard.index, v.shape)]
                    entries.append((name, index, _np.asarray(shard.data),
                                    v.shape, v.dtype))
            elif partition:
                # replicated/host leaves partitioned over the live set:
                # every host at this generation computes the same owner
                # per leaf, so the chunks tile exactly once
                if coord.owns(name):
                    arr = _np.asarray(v)
                    index = [(0, d) for d in arr.shape]
                    entries.append((name, index, arr, arr.shape, arr.dtype))
            elif proc == 0:
                arr = _np.asarray(v)
                index = [(0, d) for d in arr.shape]
                entries.append((name, index, arr, arr.shape, arr.dtype))
        nbytes = _manifest.write_shard(sdir, proc, entries)
        return nbytes, sdir, proc

    def _commit(self, sdir, step, meta, nbytes, t0, ctx=None):
        """Atomic manifest commit + retention + save telemetry."""
        import jax
        t_c0 = time.perf_counter() if _tracing._ENABLED else 0.0
        _manifest.commit(sdir, step, meta,
                         expected_processes=jax.process_count())
        _manifest.prune(self.directory, self.max_to_keep)
        seconds = time.perf_counter() - t0
        if _tracing._ENABLED:
            _tracing.record_span("mx.elastic.commit", t_c0, t0 + seconds,
                                 parent=ctx, step=step, bytes=int(nbytes))
        self.save_seconds = seconds
        self.bytes_written += int(nbytes)
        if _telem._ENABLED:
            _telem.record_checkpoint_save(seconds, nbytes, source="elastic")

    def _commit_coordinated(self, sdir, step, meta, nbytes, t0, ctx=None):
        """Two-phase cross-host commit: post this host's ready marker,
        then converge on the leader-assembled, generation-stamped global
        manifest (elastic/coordinator.py ``commit_snapshot``). Every
        participant calls this — leadership is decided by the live view
        inside the barrier, so a leader that dies mid-commit is replaced
        without any host taking a different code path. Retention runs on
        the leader only (prune itself skips dirs a live peer is still
        writing)."""
        coord = self.coordinator
        t_c0 = time.perf_counter() if _tracing._ENABLED else 0.0
        coord.write_marker(sdir, step, nbytes)
        coord.commit_snapshot(sdir, step, meta)
        if coord.view(bump=False).leader == coord.rank:
            _manifest.prune(self.directory, self.max_to_keep)
        seconds = time.perf_counter() - t0
        if _tracing._ENABLED:
            _tracing.record_span("mx.elastic.commit", t_c0, t0 + seconds,
                                 parent=ctx, step=step, bytes=int(nbytes))
        self.save_seconds = seconds
        self.bytes_written += int(nbytes)
        if _telem._ENABLED:
            _telem.record_checkpoint_save(seconds, nbytes, source="elastic")

    # -- lifecycle -----------------------------------------------------------
    def wait_until_finished(self):
        """Join the in-flight writer; re-raises a background failure (a
        snapshot that silently failed is worse than a crashed save)."""
        w = self._writer
        if w is not None:
            w.join()
            self._writer = None
        if self._error is not None:
            err, self._error = self._error, None
            raise MXNetError(f"async snapshot write failed: {err!r}") from err

    def close(self):
        self.wait_until_finished()

    def __del__(self):
        try:
            w = self._writer
            if w is not None:
                w.join(timeout=10)
        except Exception:
            pass

    # -- introspection -------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return _manifest.latest_complete_step(self.directory)

    def all_steps(self):
        return _manifest.all_complete_steps(self.directory)
