"""mxnet_tpu.elastic — fault-tolerant training (ROADMAP item 4).

Async sharded snapshots (no gather, no host sync on the step path),
resharding restore onto a different mesh, resumable input feeds,
SIGTERM-clean preemption, and a shared-filesystem multi-host control
plane — the TPU-native answer to the reference framework's ps-lite
"checkpoint + relaunch" fault model.

    manifest.py     on-disk layout + atomic manifest commit + chunk reader
    snapshot.py     SnapshotManager: async copy-then-write off the step path
    state.py        trainer capture/install incl. ZeRO re-canonicalization
    run.py          resume_or_init / PreemptionGuard / supervised run loop
    coordinator.py  heartbeat membership, coordinated stop, two-phase
                    cross-host commit, hang watchdog
    drill.py        real multi-process kill/race/straggler drill harness

See docs/checkpointing.md for anatomy, cadence tuning, resharding rules,
the preemption runbook, and the multi-host snapshot protocol.
"""
from .manifest import SnapshotReader, all_complete_steps, latest_complete_step
from .snapshot import SnapshotManager
from .state import capture, install
from .run import (PreemptionGuard, capture_trainer, resume_or_init, run,
                  save_trainer)
from .coordinator import (Coordinator, GroupView, HangWatchdog,
                          StragglerTimeout)

__all__ = [
    "SnapshotManager", "SnapshotReader", "all_complete_steps",
    "latest_complete_step", "capture", "install", "capture_trainer",
    "save_trainer", "resume_or_init", "PreemptionGuard", "run",
    "Coordinator", "GroupView", "HangWatchdog", "StragglerTimeout",
]
