"""Optimizers (reference python/mxnet/optimizer/optimizer.py + fused update
kernels in src/operator/optimizer_op.cc).

TPU-native: each optimizer's update rule is ONE jitted pure function
`(weight, grad, *states, lr, wd, ...) -> (new_weight, *new_states)`; scalars
enter as traced 0-d arrays so changing the learning rate never recompiles.
Multi-precision (`mp_*` kernels in the reference) falls out naturally: the
master weight is the f32 state and the bf16 copy is refreshed per step.
"""
from __future__ import annotations

import math
import pickle
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray, zeros
from .. import engine as _engine

_OPT_REGISTRY: Dict[str, type] = {}


def register(cls):
    _OPT_REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(name, **kwargs) -> "Optimizer":
    if isinstance(name, Optimizer):
        return name
    try:
        return _OPT_REGISTRY[name.lower()](**kwargs)
    except KeyError:
        raise MXNetError(f"unknown optimizer {name!r}") from None


def _f(x):
    return jnp.float32(x)


class Optimizer:
    """Base optimizer (reference optimizer.py:31)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 multi_precision=False, param_dict=None, begin_num_update=0,
                 aggregate_num=0, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.aggregate_num = aggregate_num
        self.param_idx2name = dict(param_idx2name or {})
        self.param_dict = dict(param_dict or {})
        self.idx2name = self.param_idx2name
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self._index_update_count: Dict[Any, int] = {}
        self._all_index_update_counts = {0: self._index_update_count}
        self.lr_mult: Dict[str, float] = {}
        self.wd_mult: Dict[str, float] = {}

    # pickling (Updater.get_states ships the optimizer to kvstore servers):
    # drop the live Parameter references, they are re-bound on the worker
    def __getstate__(self):
        st = dict(self.__dict__)
        st["param_dict"] = {}
        return st

    def __setstate__(self, st):
        self.__dict__.update(st)

    # -- bookkeeping --------------------------------------------------------
    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for i in index:
            self._index_update_count.setdefault(i, self.begin_num_update)
            self._index_update_count[i] += 1
            self.num_update = max(self._index_update_count[i], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("lr_scheduler is set; use the scheduler to change lr")
        self.lr = lr

    @property
    def learning_rate(self):
        return self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    # -- state --------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (jnp.float16, jnp.bfloat16):
            master = NDArray(weight._data.astype(jnp.float32), weight.ctx)
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    # -- update -------------------------------------------------------------
    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype in (jnp.float16, jnp.bfloat16):
            master, base_state = state
            g32 = NDArray(grad._data.astype(jnp.float32), grad.ctx)
            self.update(index, master, g32, base_state)
            weight._set_data(master._data.astype(weight.dtype))
        else:
            self.update(index, weight, grad, state)

    # list-form update used by kvstore trainer path
    def _update_list(self, indices, weights, grads, states):
        for i, w, g, s in zip(indices, weights, grads, states):
            self.update_multi_precision(i, w, g, s)

    def _preprocess(self, grad_raw, wd=None, weight_raw=None):
        g = grad_raw * _f(self.rescale_grad)
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g


class Updater:
    """Serializable state-holder applying an optimizer (reference
    optimizer.py:2018 — the object shipped to kvstore servers)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}
        self.states_synced: Dict[Any, bool] = {}
        self.aggregate_updates = optimizer.aggregate_num > 0

    def __call__(self, index, grad, weight):
        if not isinstance(index, (list, tuple)):
            index, grad, weight = [index], [grad], [weight]
        for i, g, w in zip(index, grad, weight):
            if i not in self.states:
                self.states[i] = self.optimizer.create_state_multi_precision(i, w)
                self.states_synced[i] = True
            # no _update_count here: every concrete update() counts for
            # itself (reference optimizer.py:2018 Updater likewise leaves
            # counting to the optimizer) — counting in both places made
            # num_update advance 2x per step through the Trainer path,
            # so lr schedulers decayed at twice the configured rate
            self.optimizer.update_multi_precision(i, w, g, self.states[i])

    def get_states(self, dump_optimizer=False):
        def conv(s):
            if isinstance(s, NDArray):
                return ("nd", s.asnumpy(), str(s.dtype))
            if isinstance(s, (tuple, list)):
                return ("tuple", [conv(x) for x in s])
            return ("raw", s)
        payload = {k: conv(v) for k, v in self.states.items()}
        blob = {"states": payload}
        if dump_optimizer:
            blob["optimizer"] = self.optimizer
        return pickle.dumps(blob)

    def set_states(self, states_blob):
        from ..ndarray import array
        blob = pickle.loads(states_blob)

        def unconv(s):
            tag = s[0]
            if tag == "nd":
                return array(s[1], dtype=s[2])
            if tag == "tuple":
                return tuple(unconv(x) for x in s[1])
            return s[1]
        self.states = {k: unconv(v) for k, v in blob["states"].items()}
        if "optimizer" in blob:
            self.optimizer = blob["optimizer"]
        self.states_synced = {k: False for k in self.states}


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)


# ---------------------------------------------------------------------------
# Jitted update kernels
# ---------------------------------------------------------------------------

# The update-rule math lives ONCE in ops/optimizer_ops.py (the registered
# nd.*_update ops — same wiring as the reference, whose Optimizer classes call
# the ops). These kernels jit those functions with hyperparams as traced
# scalars so per-step lr changes never retrace.
from ..ops import optimizer_ops as _oo


class _UpdateKernel:
    """Jitted optimizer update that donates the weight/state buffers when the
    backend supports input-output aliasing (engine.donation_enabled()), so
    each step's weight update mutates storage in place on TPU instead of
    allocating a second copy of every parameter and optimizer state
    (the weight-update aliasing of arXiv:2004.13336). Exposes ``__wrapped__``
    so the fused data-parallel step can inline the raw math (see
    parallel/data_parallel.py functional_optimizer)."""

    __slots__ = ("__wrapped__", "_donate", "_jit", "_donating")

    def __init__(self, fn, donate=()):
        self.__wrapped__ = fn
        self._donate = tuple(donate)
        self._jit = None
        self._donating = False

    def __call__(self, *args):
        if self._jit is None:
            # resolved lazily: the backend must not initialize at import
            self._donating = bool(self._donate) and _engine.donation_enabled()
            self._jit = jax.jit(
                self.__wrapped__,
                donate_argnums=self._donate if self._donating else ())
        if self._donating:
            _engine.record_donation(len(self._donate))
            from .. import telemetry as _telem
            if _telem._ENABLED:
                # donation savings: bytes NOT double-allocated because the
                # donated inputs alias their outputs in place
                _telem.counter(
                    "mx_donation_saved_bytes_total",
                    "Buffer bytes aliased in place by donated updates") \
                    .inc(sum(getattr(args[i], "nbytes", 0)
                             for i in self._donate))
        return self._jit(*args)


def _update_kernel(*donate):
    """Decorator: jit an update rule, donating the given argnums (the weight
    and every mutable state buffer — never the gradient, which grad_req=add
    flows may still read)."""
    def wrap(fn):
        return _UpdateKernel(fn, donate)
    return wrap


def init_functional_state(init_fn, weight, sharding=None):
    """Materialize a functional-optimizer state tree for ``weight``
    (``init_fn`` from ``parallel.functional_optimizer``).

    With ``sharding`` — the ZeRO-style sharded weight update
    (arXiv:2004.13336) passes the per-shard ``NamedSharding`` over the dp
    axis — every state leaf is CREATED under that sharding: the init runs
    as a jit with ``out_shardings``, so each replica materializes only its
    1/N shard instead of allocating the full state and resharding it (which
    would momentarily hold the replicated footprint the sharding exists to
    avoid)."""
    if sharding is None:
        return init_fn(weight)
    template = jax.eval_shape(init_fn, weight)
    if not jax.tree_util.tree_leaves(template):
        return init_fn(weight)  # stateless (plain SGD): nothing to place
    return jax.jit(init_fn, out_shardings=sharding)(weight)


@_update_kernel(0)
def _k_sgd(w, g, lr, wd, rescale, clip):
    return _oo.sgd_update(w, g, lr, wd=wd, rescale_grad=rescale,
                          clip_gradient=clip)


@_update_kernel(0, 2)
def _k_sgd_mom(w, g, mom, lr, wd, rescale, clip, momentum):
    return _oo.sgd_mom_update(w, g, mom, lr, momentum=momentum, wd=wd,
                              rescale_grad=rescale, clip_gradient=clip)


@_update_kernel(0)
def _k_sgd_lazy(w, g, lr, wd, rescale, clip):
    return _oo.sgd_lazy_update(w, g, lr, wd=wd, rescale_grad=rescale,
                               clip_gradient=clip)


@_update_kernel(0, 2)
def _k_sgd_mom_lazy(w, g, mom, lr, wd, rescale, clip, momentum):
    return _oo.sgd_mom_lazy_update(w, g, mom, lr, momentum=momentum, wd=wd,
                                   rescale_grad=rescale, clip_gradient=clip)


@_update_kernel(0, 2, 3)
def _k_adam_lazy(w, g, m, v, lr, wd, rescale, clip, beta1, beta2, eps,
                 coef1, coef2):
    lr_t = lr * jnp.sqrt(coef2) / coef1
    return _oo.adam_lazy_update(w, g, m, v, lr_t, beta1=beta1, beta2=beta2,
                                epsilon=eps, wd=wd, rescale_grad=rescale,
                                clip_gradient=clip)


def _is_lazy(opt, grad):
    """Reference gating (optimizer.py:598): lazy kicks in when the gradient
    is row_sparse and the optimizer's lazy_update flag is on."""
    return opt.lazy_update and getattr(grad, "stype", "default") == "row_sparse"


@_update_kernel(0, 2)
def _k_nag(w, g, mom, lr, wd, rescale, clip, momentum):
    return _oo.nag_mom_update(w, g, mom, lr, momentum=momentum, wd=wd,
                              rescale_grad=rescale, clip_gradient=clip)


@_update_kernel(0, 2, 3)
def _k_adam(w, g, m, v, lr, wd, rescale, clip, beta1, beta2, eps, coef1, coef2):
    # bias correction folded into lr, exactly how the reference class drives
    # the (correction-free) adam_update op
    lr_t = lr * jnp.sqrt(coef2) / coef1
    return _oo.adam_update(w, g, m, v, lr_t, beta1=beta1, beta2=beta2,
                           epsilon=eps, wd=wd, rescale_grad=rescale,
                           clip_gradient=clip)


@_update_kernel(0, 2, 3)
def _k_adamw(w, g, m, v, lr, eta, wd, rescale, clip, beta1, beta2, eps, coef1, coef2):
    g = g * rescale
    g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
    m2 = beta1 * m + (1 - beta1) * g
    v2 = beta2 * v + (1 - beta2) * g * g
    mhat = m2 / coef1
    vhat = v2 / coef2
    return w - eta * (lr * mhat / (jnp.sqrt(vhat) + eps) + wd * w), m2, v2


@_update_kernel(0, 2)
def _k_rmsprop(w, g, n, lr, wd, rescale, clip, rho, eps):
    return _oo.rmsprop_update(w, g, n, lr, rho=rho, epsilon=eps, wd=wd,
                              rescale_grad=rescale, clip_gradient=clip)


@_update_kernel(0, 2, 3, 4)
def _k_rmsprop_alex(w, g, n, gavg, delta, lr, wd, rescale, clip, rho, momentum, eps):
    return _oo.rmspropalex_update(w, g, n, gavg, delta, lr, rho=rho,
                                  momentum=momentum, epsilon=eps, wd=wd,
                                  rescale_grad=rescale, clip_gradient=clip)


@_update_kernel(0, 2)
def _k_adagrad(w, g, h, lr, wd, rescale, clip, eps):
    g = g * rescale
    g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
    g = g + wd * w
    h2 = h + g * g
    return w - lr * g / (jnp.sqrt(h2) + eps), h2


@_update_kernel(0, 2, 3)
def _k_adadelta(w, g, acc_g, acc_d, wd, rescale, clip, rho, eps):
    g = g * rescale
    g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
    g = g + wd * w
    acc_g2 = rho * acc_g + (1 - rho) * g * g
    d = jnp.sqrt(acc_d + eps) / jnp.sqrt(acc_g2 + eps) * g
    acc_d2 = rho * acc_d + (1 - rho) * d * d
    return w - d, acc_g2, acc_d2


@_update_kernel(0, 2, 3)
def _k_ftrl(w, g, z, n, lr, wd, rescale, clip, lamda1, beta):
    return _oo.ftrl_update(w, g, z, n, lr, lamda1=lamda1, beta=beta, wd=wd,
                           rescale_grad=rescale, clip_gradient=clip)


@_update_kernel(0, 2, 3)
def _k_adamax(w, g, m, u, lr, wd, rescale, clip, beta1, beta2, coef1):
    g = g * rescale
    g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
    g = g + wd * w
    m2 = beta1 * m + (1 - beta1) * g
    u2 = jnp.maximum(beta2 * u, jnp.abs(g))
    return w - (lr / coef1) * m2 / (u2 + 1e-8), m2, u2


@_update_kernel(0, 2, 3)
def _k_nadam(w, g, m, v, lr, wd, rescale, clip, beta1, beta2, eps, mschedule, mnext, coef2):
    g = g * rescale
    g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
    g = g + wd * w
    ghat = g / (1 - mschedule)
    m2 = beta1 * m + (1 - beta1) * g
    v2 = beta2 * v + (1 - beta2) * g * g
    mhat = m2 / (1 - mschedule * mnext)
    vhat = v2 / coef2
    mbar = (1 - mnext / (1 - mschedule)) * ghat + (mnext / (1 - mschedule * mnext)) * m2
    mbar = (1.0 - mnext) * ghat + mnext * mhat
    return w - lr * mbar / (jnp.sqrt(vhat) + eps), m2, v2


@_update_kernel(0, 2)
def _k_signum(w, g, mom, lr, wd, rescale, clip, momentum, wd_lh):
    return _oo.signum_update(w, g, mom, lr, momentum=momentum, wd=wd,
                             rescale_grad=rescale, clip_gradient=clip,
                             wd_lh=wd_lh)


@_update_kernel(0, 2, 3, 4)
def _k_ftml(w, g, d, v, z, lr, wd, rescale, clip, beta1, beta2, eps, t):
    return _oo.ftml_update(w, g, d, v, z, lr, t, beta1=beta1, beta2=beta2,
                           epsilon=eps, wd=wd, rescale_grad=rescale,
                           clip_grad=clip)


@_update_kernel()
def _k_dcasgd(w, g, prev_w, lr, wd, rescale, clip, lamda):
    g = g * rescale
    g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
    g = g + wd * w
    comp = lamda * g * g * (w - prev_w)
    return w - lr * (g + comp), w


@_update_kernel(0)
def _k_sgld(w, g, noise, lr, wd, rescale, clip):
    g = g * rescale
    g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
    g = g + wd * w
    return w - 0.5 * lr * g + jnp.sqrt(lr) * noise


def _norm(x):
    return jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32))))


@_update_kernel(0, 2)
def _k_lars(w, g, mom, lr, wd, rescale, clip, momentum, eta, eps):
    g = g * rescale
    g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
    wn = _norm(w)
    gn = _norm(g)
    trust = jnp.where((wn > 0) & (gn > 0), eta * wn / (gn + wd * wn + eps), 1.0)
    g = g + wd * w
    mom2 = momentum * mom + trust * lr * g
    return w - mom2, mom2


@_update_kernel(0, 2, 3)
def _k_lamb(w, g, m, v, lr, wd, rescale, clip, beta1, beta2, eps, coef1, coef2,
            lower, upper, bias_correction):
    g = g * rescale
    g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
    m2 = beta1 * m + (1 - beta1) * g
    v2 = beta2 * v + (1 - beta2) * g * g
    mhat = jnp.where(bias_correction, m2 / coef1, m2)
    vhat = jnp.where(bias_correction, v2 / coef2, v2)
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * w
    wn = jnp.clip(_norm(w), lower, upper)
    rn = _norm(r)
    trust = jnp.where(rn > 0, wn / rn, 1.0)
    return w - lr * trust * r, m2, v2


# ---------------------------------------------------------------------------
# Optimizer classes
# ---------------------------------------------------------------------------

@register
class SGD(Optimizer):
    """SGD with momentum + multi-precision (reference optimizer.py:526)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        lazy = _is_lazy(self, grad)
        if self.momentum == 0.0:
            k = _k_sgd_lazy if lazy else _k_sgd
            weight._set_data(k(weight._data, grad._data, _f(lr), _f(wd),
                               _f(self.rescale_grad), _f(clip)))
        else:
            k = _k_sgd_mom_lazy if lazy else _k_sgd_mom
            w2, m2 = k(weight._data, grad._data, state._data, _f(lr),
                       _f(wd), _f(self.rescale_grad), _f(clip),
                       _f(self.momentum))
            weight._set_data(w2)
            state._set_data(m2)


@register
class ccSGD(SGD):
    """Deprecated alias kept for reference script compatibility
    (reference optimizer.py ccSGD: 'renamed to SGD in 0.9')."""


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        w2, m2 = _k_nag(weight._data, grad._data, state._data, _f(lr), _f(wd),
                        _f(self.rescale_grad), _f(clip), _f(self.momentum))
        weight._set_data(w2)
        state._set_data(m2)


@register
class SGLD(Optimizer):
    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        from .. import random as _rng
        noise = jax.random.normal(_rng.next_key(), weight.shape, jnp.float32).astype(weight.dtype)
        weight._set_data(_k_sgld(weight._data, grad._data, noise, _f(lr), _f(wd),
                                 _f(self.rescale_grad), _f(clip)))


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        w2, m2 = _k_signum(weight._data, grad._data, state._data, _f(lr), _f(wd),
                           _f(self.rescale_grad), _f(clip), _f(self.momentum),
                           _f(self.wd_lh))
        weight._set_data(w2)
        state._set_data(m2)


@register
class DCASGD(Optimizer):
    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.lamda = lamda

    def create_state(self, index, weight):
        return NDArray(weight._data, weight.ctx)  # previous weight snapshot

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        w2, prev = _k_dcasgd(weight._data, grad._data, state._data, _f(lr), _f(wd),
                             _f(self.rescale_grad), _f(clip), _f(self.lamda))
        weight._set_data(w2)
        state._set_data(prev)


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)
        return (zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype), z)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        d, v, z = state
        w2, d2, v2, z2 = _k_ftml(weight._data, grad._data, d._data, v._data,
                                 z._data, _f(lr), _f(wd), _f(self.rescale_grad),
                                 _f(clip), _f(self.beta1), _f(self.beta2),
                                 _f(self.epsilon), _f(t))
        weight._set_data(w2); d._set_data(d2); v._set_data(v2); z._set_data(z2)


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference optimizer.py:797)."""

    def __init__(self, momentum=0.0, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        w2, m2 = _k_lars(weight._data, grad._data, state._data, _f(lr), _f(wd),
                         _f(self.rescale_grad), _f(clip), _f(self.momentum),
                         _f(self.eta), _f(self.epsilon))
        weight._set_data(w2)
        state._set_data(m2)


@register
class LBSGD(SGD):
    """Large-batch SGD with warmup (reference optimizer.py LBSGD); the
    layer-wise scaling part is LARS — compose with lr warmup schedulers."""

    def __init__(self, warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(**kwargs)


@register
class LAMB(Optimizer):
    """reference optimizer.py:1250."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-6,
                 lower_bound=None, upper_bound=None, bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.ctx, dtype="float32"),
                zeros(weight.shape, ctx=weight.ctx, dtype="float32"))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        m, v = state
        w2, m2, v2 = _k_lamb(weight._data, grad._data, m._data, v._data, _f(lr),
                             _f(wd), _f(self.rescale_grad), _f(clip),
                             _f(self.beta1), _f(self.beta2), _f(self.epsilon),
                             _f(1 - self.beta1 ** t), _f(1 - self.beta2 ** t),
                             _f(self.lower_bound or 0.0),
                             _f(self.upper_bound or jnp.inf),
                             jnp.bool_(self.bias_correction))
        weight._set_data(w2); m._set_data(m2); v._set_data(v2)


@register
class Adam(Optimizer):
    """reference optimizer.py:1495."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        m, v = state
        k = _k_adam_lazy if _is_lazy(self, grad) else _k_adam
        w2, m2, v2 = k(weight._data, grad._data, m._data, v._data, _f(lr),
                       _f(wd), _f(self.rescale_grad), _f(clip),
                       _f(self.beta1), _f(self.beta2), _f(self.epsilon),
                       _f(1 - self.beta1 ** t), _f(1 - self.beta2 ** t))
        weight._set_data(w2); m._set_data(m2); v._set_data(v2)


@register
class AdamW(Adam):
    """Decoupled weight decay (reference contrib adamw.cc); eta is the
    schedule multiplier."""

    def __init__(self, eta=1.0, **kwargs):
        super().__init__(**kwargs)
        self.eta = eta

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        m, v = state
        w2, m2, v2 = _k_adamw(weight._data, grad._data, m._data, v._data, _f(lr),
                              _f(self.eta), _f(wd), _f(self.rescale_grad), _f(clip),
                              _f(self.beta1), _f(self.beta2), _f(self.epsilon),
                              _f(1 - self.beta1 ** t), _f(1 - self.beta2 ** t))
        weight._set_data(w2); m._set_data(m2); v._set_data(v2)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        w2, h2 = _k_adagrad(weight._data, grad._data, state._data, _f(lr), _f(wd),
                            _f(self.rescale_grad), _f(clip), _f(self.float_stable_eps))
        weight._set_data(w2)
        state._set_data(h2)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2, self.epsilon = gamma1, gamma2, epsilon
        self.centered = centered

    def create_state(self, index, weight):
        n = zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)
        if self.centered:
            return (n, zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
                    zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype))
        return n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        if self.centered:
            n, gavg, delta = state
            w2, n2, gavg2, d2 = _k_rmsprop_alex(
                weight._data, grad._data, n._data, gavg._data, delta._data,
                _f(lr), _f(wd), _f(self.rescale_grad), _f(clip), _f(self.gamma1),
                _f(self.gamma2), _f(self.epsilon))
            weight._set_data(w2); n._set_data(n2); gavg._set_data(gavg2); delta._set_data(d2)
        else:
            w2, n2 = _k_rmsprop(weight._data, grad._data, state._data, _f(lr),
                                _f(wd), _f(self.rescale_grad), _f(clip),
                                _f(self.gamma1), _f(self.epsilon))
            weight._set_data(w2)
            state._set_data(n2)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        acc_g, acc_d = state
        w2, g2, d2 = _k_adadelta(weight._data, grad._data, acc_g._data, acc_d._data,
                                 _f(wd), _f(self.rescale_grad), _f(clip),
                                 _f(self.rho), _f(self.epsilon))
        weight._set_data(w2); acc_g._set_data(g2); acc_d._set_data(d2)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        z, n = state
        w2, z2, n2 = _k_ftrl(weight._data, grad._data, z._data, n._data, _f(lr),
                             _f(wd), _f(self.rescale_grad), _f(clip),
                             _f(self.lamda1), _f(self.beta))
        weight._set_data(w2); z._set_data(z2); n._set_data(n2)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        m, u = state
        w2, m2, u2 = _k_adamax(weight._data, grad._data, m._data, u._data, _f(lr),
                               _f(wd), _f(self.rescale_grad), _f(clip),
                               _f(self.beta1), _f(self.beta2),
                               _f(1 - self.beta1 ** t))
        weight._set_data(w2); m._set_data(m2); u._set_data(u2)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        clip = self.clip_gradient if self.clip_gradient is not None else -1.0
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m, v = state
        w2, m2, v2 = _k_nadam(weight._data, grad._data, m._data, v._data, _f(lr),
                              _f(wd), _f(self.rescale_grad), _f(clip),
                              _f(self.beta1), _f(self.beta2), _f(self.epsilon),
                              _f(self.m_schedule), _f(momentum_t1),
                              _f(1 - self.beta2 ** t))
        weight._set_data(w2); m._set_data(m2); v._set_data(v2)


@register
class Test(Optimizer):
    """Test optimizer (reference optimizer.py:1979) — w -= lr*g, keeps a
    state buffer for kvstore-server round-trip tests."""

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        weight._set_data(_k_sgd(weight._data, grad._data, _f(self._get_lr(index)),
                                _f(self._get_wd(index)), _f(self.rescale_grad),
                                _f(-1.0)))


ccSGD = SGD
