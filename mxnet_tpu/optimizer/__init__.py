from .optimizer import (Optimizer, Updater, get_updater, create, register,
                        SGD, NAG, SGLD, Signum, DCASGD, FTML, LARS, LAMB, LBSGD,
                        Adam, AdamW, AdaGrad, AdaDelta, RMSProp, Ftrl, Adamax,
                        Nadam, Test, init_functional_state)

__all__ = ["Optimizer", "Updater", "get_updater", "create", "register",
           "SGD", "NAG", "SGLD", "Signum", "DCASGD", "FTML", "LARS", "LAMB",
           "LBSGD", "Adam", "AdamW", "AdaGrad", "AdaDelta", "RMSProp", "Ftrl",
           "Adamax", "Nadam", "Test", "init_functional_state"]
