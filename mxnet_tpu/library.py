"""Runtime operator-library loading (reference python/mxnet/library.py:28
`mx.library.load` -> MXLoadLib, include/mxnet/lib_api.h).

The reference loads a compiled .so exporting the C operator ABI. Here custom
operators are pure-jax functions registered through the same registry the
built-ins use, so an "operator library" is a Python module (or package
directory) that calls `mxnet_tpu.ops.register(...)` at import time. `load`
imports it by file path and reports the newly registered operators — after
which they are live in `mx.nd`, `mx.sym` and hybridized blocks exactly like
MXLoadLib-loaded ops were.
"""
from __future__ import annotations

import importlib.util
import os
import sys

from .base import MXNetError
from .ops.registry import all_ops


def load(path, verbose=True):
    """Load an operator library (a Python module registering ops).

    Returns the list of operator names the library registered.
    """
    if not os.path.exists(path):
        raise MXNetError(f"library not found: {path}")
    if path.endswith(".so"):
        raise MXNetError(
            "compiled operator libraries use the reference's C ABI; here an "
            "operator library is a Python module calling "
            "mxnet_tpu.ops.register — see mxnet_tpu/operator.py for the "
            "CustomOp alternative")
    if os.path.isdir(path):
        init = os.path.join(path, "__init__.py")
        if not os.path.exists(init):
            raise MXNetError(
                f"operator-library package {path} has no __init__.py")
        path = init
    before = set(all_ops())
    name = f"mxnet_tpu_oplib_{os.path.basename(os.path.dirname(path) if path.endswith('__init__.py') else path).rsplit('.', 1)[0]}"
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise MXNetError(f"cannot import operator library {path}")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    new_ops = sorted(set(all_ops()) - before)
    if verbose:
        for op in new_ops:
            print(f"loaded op: {op}")
    return new_ops
