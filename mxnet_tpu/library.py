"""Runtime operator-library loading (reference python/mxnet/library.py:28
`mx.library.load` -> MXLoadLib, include/mxnet/lib_api.h).

Two library flavors, both landing in the SAME op registry the built-ins
use (so loaded ops are live in `mx.nd`, `mx.sym`, and hybridized blocks):

1. **Python libraries** — a module/package calling
   `mxnet_tpu.ops.register(...)` at import time; `load` imports it by
   path and reports the new ops.
2. **Compiled `.so` libraries** — the TPU-native analog of the
   reference's binary custom-op ABI (include/mxnet/lib_api.h:1-1023).
   The .so exports the `mxtpu_oplib_*` C symbols (see
   src/native/oplib_example.cc); each exported op is registered with a
   `jax.pure_callback` implementation, so the compiled host kernel runs
   under jit/XLA exactly where the reference's CustomOp ran on the
   engine. ABI v1 is float32, single-output, forward-only — custom
   gradients go through the Python `operator.CustomOp` path.
"""
from __future__ import annotations

import ctypes
import importlib.util
import os
import sys

from .base import MXNetError
from .ops.registry import all_ops

_MAX_NDIM = 8


def _load_binary(path, verbose=True):
    """Load a compiled operator library exporting the mxtpu_oplib ABI."""
    import numpy as _np
    import jax
    import jax.numpy as jnp
    from .ops.registry import register

    lib = ctypes.CDLL(os.path.abspath(path))
    try:
        lib.mxtpu_oplib_abi_version.restype = ctypes.c_int
        lib.mxtpu_oplib_count.restype = ctypes.c_int
        lib.mxtpu_oplib_name.restype = ctypes.c_char_p
        lib.mxtpu_oplib_name.argtypes = [ctypes.c_int]
        P64 = ctypes.POINTER(ctypes.c_int64)
        PF = ctypes.POINTER(ctypes.c_float)
        lib.mxtpu_oplib_infer.restype = ctypes.c_int
        lib.mxtpu_oplib_infer.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(P64),
            ctypes.POINTER(ctypes.c_int), P64, ctypes.POINTER(ctypes.c_int)]
        lib.mxtpu_oplib_forward.restype = ctypes.c_int
        lib.mxtpu_oplib_forward.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(PF),
            ctypes.POINTER(P64), ctypes.POINTER(ctypes.c_int),
            PF, P64, ctypes.c_int]
    except AttributeError as e:
        raise MXNetError(
            f"{path} does not export the mxtpu_oplib ABI "
            f"(src/native/oplib_example.cc documents it): {e}")
    ver = lib.mxtpu_oplib_abi_version()
    if ver != 1:
        raise MXNetError(f"unsupported oplib ABI version {ver} (want 1)")

    def _shape_args(arrs):
        shapes = [_np.asarray(a.shape, _np.int64) for a in arrs]
        shape_ptrs = (P64 * len(arrs))(
            *[s.ctypes.data_as(P64) for s in shapes])
        ndims = (ctypes.c_int * len(arrs))(*[a.ndim for a in arrs])
        return shapes, shape_ptrs, ndims

    def _infer(idx, arrs):
        for a in arrs:
            if a.ndim > _MAX_NDIM:
                raise MXNetError(
                    f"oplib ABI v1 supports at most {_MAX_NDIM} dims, "
                    f"got input with {a.ndim}")
        _, shape_ptrs, ndims = _shape_args(arrs)
        # the ABI caps outputs at the max input rank <= _MAX_NDIM, so the
        # buffer cannot be overrun by a conforming library; out_ndim is
        # validated regardless
        out_shape = _np.zeros(_MAX_NDIM, _np.int64)
        out_ndim = ctypes.c_int(0)
        rc = lib.mxtpu_oplib_infer(idx, len(arrs), shape_ptrs, ndims,
                                   out_shape.ctypes.data_as(P64),
                                   ctypes.byref(out_ndim))
        if rc != 0:
            raise MXNetError(
                f"oplib infer failed (op #{idx}, shapes "
                f"{[a.shape for a in arrs]})")
        if not 0 <= out_ndim.value <= _MAX_NDIM:
            raise MXNetError(
                f"oplib infer returned out_ndim={out_ndim.value} "
                f"(ABI v1 max {_MAX_NDIM})")
        return tuple(int(s) for s in out_shape[:out_ndim.value])

    def _make_impl(idx, opname):
        def host_fn(out_shape, *arrs):
            # out_shape was computed ONCE at trace time; the callback
            # only runs the compiled forward
            arrs = [_np.ascontiguousarray(_np.asarray(a, _np.float32))
                    for a in arrs]
            out = _np.zeros(out_shape, _np.float32)
            shapes, shape_ptrs, ndims = _shape_args(arrs)
            in_ptrs = (PF * len(arrs))(
                *[a.ctypes.data_as(PF) for a in arrs])
            oshape = _np.asarray(out_shape, _np.int64)
            rc = lib.mxtpu_oplib_forward(
                idx, len(arrs), in_ptrs, shape_ptrs, ndims,
                out.ctypes.data_as(PF), oshape.ctypes.data_as(P64),
                len(out_shape))
            if rc != 0:
                # NB: raised inside the XLA host callback — JAX surfaces
                # it as XlaRuntimeError at the sync point; the message
                # below stays visible in that error's cause chain
                raise MXNetError(f"oplib forward failed for {opname!r}")
            return out

        def impl(*raw):
            # the compiled host kernel runs as a callback under jit/XLA —
            # the portable XLA-FFI-style hook for external binaries.
            # shapes are static under trace, so infer runs at trace time
            import functools
            out_shape = _infer(idx, [jnp.asarray(r) for r in raw])
            res = jax.ShapeDtypeStruct(out_shape, jnp.float32)
            return jax.pure_callback(functools.partial(host_fn, out_shape),
                                     res, *raw)

        impl.__name__ = opname
        return impl

    n = lib.mxtpu_oplib_count()
    # validate the whole export list BEFORE registering anything: a
    # mid-loop failure must not leave half the library registered
    existing = set(all_ops())
    exported = []
    for i in range(n):
        raw_name = lib.mxtpu_oplib_name(i)
        if not raw_name:
            continue
        opname = raw_name.decode()
        if opname in existing:
            raise MXNetError(
                f"operator library {os.path.basename(path)} exports "
                f"{opname!r}, which would overwrite an existing operator — "
                "rename it in the library")
        existing.add(opname)  # catches duplicate exports within the .so
        exported.append((i, opname))
    names = []
    for i, opname in exported:
        register(opname, differentiable=False)(_make_impl(i, opname))
        names.append(opname)
        if verbose:
            print(f"loaded op: {opname} (binary, {os.path.basename(path)})")
    return names


def load(path, verbose=True):
    """Load an operator library — a compiled `.so` exporting the
    mxtpu_oplib ABI, or a Python module registering ops.

    Returns the list of operator names the library registered.
    """
    if not os.path.exists(path):
        raise MXNetError(f"library not found: {path}")
    if path.endswith(".so"):
        return _load_binary(path, verbose=verbose)
    if os.path.isdir(path):
        init = os.path.join(path, "__init__.py")
        if not os.path.exists(init):
            raise MXNetError(
                f"operator-library package {path} has no __init__.py")
        path = init
    before = set(all_ops())
    name = f"mxnet_tpu_oplib_{os.path.basename(os.path.dirname(path) if path.endswith('__init__.py') else path).rsplit('.', 1)[0]}"
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise MXNetError(f"cannot import operator library {path}")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    new_ops = sorted(set(all_ops()) - before)
    if verbose:
        for op in new_ops:
            print(f"loaded op: {op}")
    return new_ops
