"""Misc utilities: numpy-shape scopes (reference python/mxnet/util.py)."""
from __future__ import annotations

import functools
import threading

_state = threading.local()


def _flags():
    if not hasattr(_state, "np_shape"):
        _state.np_shape = True   # TPU build is numpy-semantics by default
        _state.np_array = True
    return _state


def is_np_shape() -> bool:
    return _flags().np_shape


def is_np_array() -> bool:
    return _flags().np_array


def set_np_shape(active: bool) -> bool:
    st = _flags()
    prev, st.np_shape = st.np_shape, active
    return prev


def set_np(shape=True, array=True):
    st = _flags()
    st.np_shape, st.np_array = shape, array


def reset_np():
    set_np(True, True)


class np_shape:
    """Context manager parity with mx.util.np_shape."""

    def __init__(self, active=True):
        self._active = active

    def __enter__(self):
        self._prev = set_np_shape(self._active)
        return self

    def __exit__(self, *exc):
        set_np_shape(self._prev)


def use_np_shape(func):
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with np_shape(True):
            return func(*args, **kwargs)
    return wrapper


def use_np(func):
    return use_np_shape(func)


def get_gpu_count():
    from .context import num_tpus
    return num_tpus()


def get_gpu_memory(dev_id=0):
    import jax
    try:
        stats = jax.devices()[dev_id].memory_stats()
        return stats.get("bytes_in_use", 0), stats.get("bytes_limit", 0)
    except Exception:
        return (0, 0)
