"""Monitor (reference python/mxnet/monitor.py): installs a per-output
callback on executors to dump activation/weight statistics every N batches —
the debugging analog of executor monitor callbacks
(SetMonitorCallback, reference src/executor/graph_executor.cc:187).
"""
from __future__ import annotations

import logging
import re
import warnings
from typing import Callable, List, Optional, Tuple

from .ndarray import NDArray

_HYBRID_MSG = (
    "Monitor taps on a hybridized HybridBlock see nothing: the fused engine "
    "path runs one compiled artifact and bypasses per-child forward hooks "
    "(they only fire during the trace, with abstract values). Call "
    "hybridize(active=False) on the monitored block, or install the monitor "
    "on an un-hybridized copy for debugging.")


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                """mean absolute value — the reference default |x|/size"""
                import jax.numpy as jnp
                return NDArray(jnp.mean(jnp.abs(x._data)))
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue: List[Tuple[int, str, NDArray]] = []
        self.step = 0
        self.exes = []
        self.blocks = []
        self._warned_hybrid = False
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def _check_hybridized(self):
        if self._warned_hybrid:
            return
        hyb = [type(b).__name__ for b in self.blocks
               if getattr(b, "_active", False)]
        if hyb:
            self._warned_hybrid = True
            warnings.warn(f"{_HYBRID_MSG} (hybridized: {hyb})", UserWarning,
                          stacklevel=3)

    def install(self, exe):
        """Attach to an Executor (reference monitor.py:79 install_to_executor)."""
        def stat_helper(name, arr):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(arr)))
        exe.set_monitor_callback(stat_helper)
        self.exes.append(exe)
        return exe

    def tic(self):
        """Start collecting for this batch if due (reference monitor.py:87)."""
        self._check_hybridized()
        if self.step % self.interval == 0:
            for exe in self.exes:
                for arr in exe.arg_arrays:
                    arr.wait_to_read()
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self) -> List[Tuple[int, str, str]]:
        """Stop collecting; also dump weights (reference monitor.py:96)."""
        if not self.activated:
            return []
        for exe in self.exes:
            for name, array in zip(exe._symbol.list_arguments(),
                                   exe.arg_arrays):
                if self.re_prog.match(name):
                    self.queue.append((self.step, name, self.stat_func(array)))
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            s = " ".join(str(float(v.asnumpy().reshape(-1)[0]))
                         if v.size == 1 else str(v.asnumpy()) for v in v_list)
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        """(reference monitor.py:124)"""
        for n, k, v in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, v)

    def install_block(self, block):
        """Attach to a gluon Block via forward hooks: records the same
        mean-|x| statistics per child block output (the gluon-era analog of
        install_to_executor; reference monitor only covered executors).

        NOTE: a hybridized HybridBlock's fused engine path bypasses forward
        hooks (one compiled artifact per signature — children never run
        eagerly), so taps see nothing; install/tic raise a UserWarning in
        that case instead of silently returning empty stats."""
        self.blocks.append(block)
        self._check_hybridized()

        def hook(blk, inputs, output, _prefix=getattr(block, "_prefix", "")):
            if not self.activated:
                return
            from .gluon.block import in_trace
            if in_trace():
                # fused-path trace: outputs are abstract tracers; recording
                # them would leak tracers into toc()/asnumpy
                return
            name = getattr(blk, "_prefix", "") or type(blk).__name__
            if not self.re_prog.match(name):
                return
            outs = output if isinstance(output, (list, tuple)) else [output]
            for i, o in enumerate(outs):
                if isinstance(o, NDArray):
                    self.queue.append(
                        (self.step, f"{name}output{i if i else ''}",
                         self.stat_func(o)))
        handles = []
        for child in block._children.values() if hasattr(block, "_children") \
                else []:
            handles.append(child.register_forward_hook(hook))
        if not handles:
            handles.append(block.register_forward_hook(hook))
        return handles
