"""Inception v3 (reference gluon/model_zoo/vision/inception.py)."""
from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["Inception3", "inception_v3"]


def _make_basic_conv(**kwargs):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


def _make_branch(use_pool, *conv_settings):
    out = nn.HybridSequential()
    if use_pool == "avg":
        out.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(pool_size=3, strides=2))
    for setting in conv_settings:
        kwargs = {}
        for k, v in zip(("channels", "kernel_size", "strides", "padding"), setting):
            if v is not None:
                kwargs[k] = v
        out.add(_make_basic_conv(**kwargs))
    return out


class _Concurrent(HybridBlock):
    """Run children on the same input and concat on channels."""

    def add(self, block):
        self.register_child(block)

    def hybrid_forward(self, F, x):
        outs = [b(x) for b in self._children.values()]
        return F.Concat(*outs, dim=1)


def _make_A(pool_features):
    out = _Concurrent()
    out.add(_make_branch(None, (64, 1, None, None)))
    out.add(_make_branch(None, (48, 1, None, None), (64, 5, None, 2)))
    out.add(_make_branch(None, (64, 1, None, None), (96, 3, None, 1), (96, 3, None, 1)))
    out.add(_make_branch("avg", (pool_features, 1, None, None)))
    return out


def _make_B():
    out = _Concurrent()
    out.add(_make_branch(None, (384, 3, 2, None)))
    out.add(_make_branch(None, (64, 1, None, None), (96, 3, None, 1), (96, 3, 2, None)))
    out.add(_make_branch("max"))
    return out


def _make_C(channels_7x7):
    out = _Concurrent()
    out.add(_make_branch(None, (192, 1, None, None)))
    out.add(_make_branch(None, (channels_7x7, 1, None, None),
                         (channels_7x7, (1, 7), None, (0, 3)),
                         (192, (7, 1), None, (3, 0))))
    out.add(_make_branch(None, (channels_7x7, 1, None, None),
                         (channels_7x7, (7, 1), None, (3, 0)),
                         (channels_7x7, (1, 7), None, (0, 3)),
                         (channels_7x7, (7, 1), None, (3, 0)),
                         (192, (1, 7), None, (0, 3))))
    out.add(_make_branch("avg", (192, 1, None, None)))
    return out


def _make_D():
    out = _Concurrent()
    out.add(_make_branch(None, (192, 1, None, None), (320, 3, 2, None)))
    out.add(_make_branch(None, (192, 1, None, None), (192, (1, 7), None, (0, 3)),
                         (192, (7, 1), None, (3, 0)), (192, 3, 2, None)))
    out.add(_make_branch("max"))
    return out


class _BranchSplit(HybridBlock):
    def __init__(self, stem, b1, b2, **kw):
        super().__init__(**kw)
        self.stem = stem
        self.b1 = b1
        self.b2 = b2

    def hybrid_forward(self, F, x):
        s = self.stem(x)
        return F.Concat(self.b1(s), self.b2(s), dim=1)


def _make_E():
    out = _Concurrent()
    out.add(_make_branch(None, (320, 1, None, None)))
    out.add(_BranchSplit(_make_basic_conv(channels=384, kernel_size=1),
                         _make_basic_conv(channels=384, kernel_size=(1, 3),
                                          padding=(0, 1)),
                         _make_basic_conv(channels=384, kernel_size=(3, 1),
                                          padding=(1, 0))))
    out.add(_BranchSplit(
        nn.HybridSequential(),
        _make_basic_conv(channels=384, kernel_size=(1, 3), padding=(0, 1)),
        _make_basic_conv(channels=384, kernel_size=(3, 1), padding=(1, 0))))
    out.add(_make_branch("avg", (192, 1, None, None)))
    return out


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(_make_basic_conv(channels=32, kernel_size=3, strides=2))
        self.features.add(_make_basic_conv(channels=32, kernel_size=3))
        self.features.add(_make_basic_conv(channels=64, kernel_size=3, padding=1))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_basic_conv(channels=80, kernel_size=1))
        self.features.add(_make_basic_conv(channels=192, kernel_size=3))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_A(32))
        self.features.add(_make_A(64))
        self.features.add(_make_A(64))
        self.features.add(_make_B())
        self.features.add(_make_C(128))
        self.features.add(_make_C(160))
        self.features.add(_make_C(160))
        self.features.add(_make_C(192))
        self.features.add(_make_D())
        self.features.add(_make_E())
        self.features.add(_make_E())
        self.features.add(nn.AvgPool2D(pool_size=8))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise MXNetError("pretrained weights unavailable (zero egress)")
    return Inception3(**kwargs)
