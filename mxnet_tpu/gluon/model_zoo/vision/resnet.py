"""ResNet v1/v2 (reference gluon/model_zoo/vision/resnet.py).

Same architecture graph as the reference zoo (whose pretrained resnet-50
scores 0.7527 top-1, BASELINE.md); built from this framework's layers so the
whole net lowers to one fused XLA program under hybridize()."""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1", "ResNetV2", "SpaceToDepthStem",
           "resnet18_v1", "resnet34_v1", "resnet50_v1",
           "resnet101_v1", "resnet152_v1", "resnet18_v2", "resnet34_v2",
           "resnet50_v2", "resnet101_v2", "resnet152_v2", "get_resnet"]


def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, kernel_size=1, strides=stride,
                                          use_bias=False, in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x2 = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(x2 + residual, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, kernel_size=1, strides=stride,
                                          use_bias=False, in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x2 = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(x2 + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1, use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1, use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class SpaceToDepthStem(HybridBlock):
    """Math-equivalent replacement for the 7x7/stride-2 stem conv — the
    classic TPU ResNet transform (MLPerf reference implementations):
    space_to_depth(2) folds the stride into channels, turning the
    7x7/s2 conv over 3 channels (an MXU-hostile shape: 147-deep
    contraction, stride-2 halo) into a 4x4/s1 conv over 12 channels.
    The parameter KEEPS the original (64, 3, 7, 7) layout — plain-stem
    weights copy straight in — and the forward rearranges it:
    zero-pad 7x7 -> 8x8 (top/left, compensating the odd pad=3), then view
    each 2x2 tap block as one tap over the s2d (dy, dx, c) channel order.
    Outputs equal the original conv up to float reduction order."""

    def __init__(self, channels=64, in_channels=3, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self.weight = self.params.get("weight",
                                      shape=(channels, in_channels, 7, 7))

    def hybrid_forward(self, F, x, weight):
        O, C = self._channels, weight.shape[1]
        x = F.space_to_depth(x, block_size=2)
        # original pad=3 becomes asymmetric (2, 1) in block space
        x = F.pad(x, mode="constant",
                  pad_width=(0, 0, 0, 0, 2, 1, 2, 1))
        w8 = F.pad(weight.reshape((1, O * C, 7, 7)), mode="constant",
                   pad_width=(0, 0, 0, 0, 1, 0, 1, 0))
        w8 = w8.reshape((O, C, 4, 2, 4, 2))
        w = w8.transpose((0, 3, 5, 1, 2, 4)).reshape((O, 4 * C, 4, 4))
        return F.Convolution(x, w, None, kernel=(4, 4), stride=(1, 1),
                             pad=(0, 0), num_filter=O, no_bias=True)


class ResNetV1(HybridBlock):
    """s2d_stem=True swaps the 7x7/s2 stem conv for the math-equivalent
    SpaceToDepthStem (same parameter shape, same outputs, MXU-friendly)."""

    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 s2d_stem=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self.features = nn.HybridSequential()
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0))
        else:
            if s2d_stem:
                self.features.add(SpaceToDepthStem(channels[0]))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(block, num_layer, channels[i + 1],
                                               stride, in_channels=channels[i]))
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, in_channels=0):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self.features = nn.HybridSequential()
        self.features.add(nn.BatchNorm(scale=False, center=False))
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        in_channels = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(block, num_layer, channels[i + 1],
                                               stride, in_channels=in_channels))
            in_channels = channels[i + 1]
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes, in_units=in_channels)

    _make_layer = ResNetV1._make_layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None, **kwargs):
    if pretrained:
        raise MXNetError("pretrained weights unavailable (zero egress); "
                         "load_parameters() from a local .params file instead")
    block_type, layers, channels = resnet_spec[num_layers]
    net_cls = resnet_net_versions[version - 1]
    block_cls = resnet_block_versions[version - 1][block_type]
    return net_cls(block_cls, layers, channels, **kwargs)


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
