"""Gluon imperative API (reference python/mxnet/gluon/)."""
from .parameter import Parameter, Constant, ParameterDict, DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import rnn
from . import loss
from . import data
from . import utils
from . import contrib
from ..import initializer as init  # mx.gluon.init alias parity

__all__ = ["Parameter", "Constant", "ParameterDict", "Block", "HybridBlock",
           "SymbolBlock", "Trainer", "nn", "rnn", "loss", "data", "utils",
           "init", "model_zoo"]


def __getattr__(name):
    if name == "model_zoo":
        # importlib, not `from . import`: the latter re-enters this
        # __getattr__ mid-import and recurses
        import importlib
        mz = importlib.import_module(".model_zoo", __name__)
        globals()["model_zoo"] = mz
        return mz
    raise AttributeError(f"module 'mxnet_tpu.gluon' has no attribute '{name}'")
