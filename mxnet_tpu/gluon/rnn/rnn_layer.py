"""Fused RNN layers (reference python/mxnet/gluon/rnn/rnn_layer.py:307-535).

Parameters are stored per-layer/gate (i2h/h2h weight+bias, cuDNN gate order)
and packed into the fused RNN op's flat vector at call time — checkpoint
layout matches the reference's unfused view.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...base import MXNetError
from ...ndarray import NDArray
from ..block import HybridBlock


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode,
                 prefix=None, params=None):
        super().__init__(prefix, params)
        assert layout in ("TNC", "NTC"), f"invalid layout {layout}"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in (["l", "r"] if bidirectional else ["l"]):
                setattr(self, f"{j}{i}_i2h_weight",
                        self.params.get(f"{j}{i}_i2h_weight", shape=(ng * nh, ni),
                                        init=i2h_weight_initializer,
                                        allow_deferred_init=True))
                setattr(self, f"{j}{i}_h2h_weight",
                        self.params.get(f"{j}{i}_h2h_weight", shape=(ng * nh, nh),
                                        init=h2h_weight_initializer,
                                        allow_deferred_init=True))
                setattr(self, f"{j}{i}_i2h_bias",
                        self.params.get(f"{j}{i}_i2h_bias", shape=(ng * nh,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True))
                setattr(self, f"{j}{i}_h2h_bias",
                        self.params.get(f"{j}{i}_h2h_bias", shape=(ng * nh,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True))
            ni = nh * self._dir

    def infer_shape(self, x, *args):
        isz = x.shape[2] if self._layout == "TNC" else x.shape[2]
        ng, nh = self._gates, self._hidden_size
        ni = isz
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                getattr(self, f"{j}{i}_i2h_weight").shape = (ng * nh, ni)
            ni = nh * self._dir

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, ctx=None, **kwargs):
        from ... import ndarray as nd
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            states.append(func(shape=info["shape"], ctx=ctx, **kwargs)
                          if "shape" in info else func(**info, **kwargs))
        return states

    def _collect_param_list(self):
        names = []
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                names.append((f"{j}{i}_i2h_weight", f"{j}{i}_h2h_weight"))
        bias = []
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                bias.append((f"{j}{i}_i2h_bias", f"{j}{i}_h2h_bias"))
        return names, bias

    def hybrid_forward(self, F, x, *states, **params):
        if self._layout == "NTC":
            x = F.swapaxes(x, dim1=0, dim2=1)
        batch = x.shape[1]
        if not states:
            states = None
        if states is None:
            states = self.begin_state(batch, ctx=x.ctx, dtype=x.dtype)
            states_given = False
        else:
            states = list(states[0]) if isinstance(states[0], (list, tuple)) else list(states)
            states_given = True
        # pack flat parameter vector (weights then biases, cuDNN layout)
        wn, bn = self._collect_param_list()
        flats = []
        for a, b in wn:
            flats.append(params[a].reshape((-1,)))
            flats.append(params[b].reshape((-1,)))
        for a, b in bn:
            flats.append(params[a].reshape((-1,)))
            flats.append(params[b].reshape((-1,)))
        flat = F.Concat(*flats, dim=0) if len(flats) > 1 else flats[0]
        rnn_args = [x, flat, states[0]]
        if self._mode == "lstm":
            rnn_args.append(states[1])
        outs = F.RNN(*rnn_args, state_size=self._hidden_size,
                     num_layers=self._num_layers, mode=self._mode,
                     bidirectional=self._dir == 2, p=self._dropout,
                     state_outputs=True)
        out = outs[0]
        out_states = list(outs[1:])
        if self._layout == "NTC":
            out = F.swapaxes(out, dim1=0, dim2=1)
        if states_given:
            return out, out_states
        return out

    def __call__(self, x, *states):
        return super().__call__(x, *states)


class RNN(_RNNLayer):
    """Vanilla multi-layer Elman RNN (reference rnn_layer.py:307)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu", layout="TNC",
                 dropout=0, bidirectional=False, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer, mode,
                         prefix, params)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size, self._hidden_size)}]


class LSTM(_RNNLayer):
    """reference rnn_layer.py:389."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer, "lstm",
                         prefix, params)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape}, {"shape": shape}]


class GRU(_RNNLayer):
    """reference rnn_layer.py:476."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer, "gru",
                         prefix, params)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size, self._hidden_size)}]
