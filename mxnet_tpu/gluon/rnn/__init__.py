from .rnn_layer import RNN, LSTM, GRU
from .rnn_cell import (RecurrentCell, RNNCell, LSTMCell, GRUCell,
                       SequentialRNNCell, HybridSequentialRNNCell,
                       DropoutCell, ZoneoutCell,
                       ResidualCell, BidirectionalCell, HybridRecurrentCell)

__all__ = ["RNN", "LSTM", "GRU", "RecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell", "HybridRecurrentCell"]
