"""RNN cell API (reference python/mxnet/gluon/rnn/rnn_cell.py)."""
from __future__ import annotations

from ...base import MXNetError
from ...ndarray import NDArray
from ..block import HybridBlock


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, ctx=None, **kwargs):
        from ... import ndarray as nd
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            states.append(func(shape=info["shape"], ctx=ctx, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as nd
        axis = layout.find("T")
        if isinstance(inputs, NDArray):
            parts = nd.SliceChannel(inputs, num_outputs=length, axis=axis,
                                    squeeze_axis=True)
            inputs = parts if isinstance(parts, list) else [parts]
        batch = inputs[0].shape[0]
        states = begin_state or self.begin_state(batch, ctx=inputs[0].ctx,
                                                 dtype=inputs[0].dtype)
        outputs = []
        for t in range(length):
            out, states = self(inputs[t], states)
            outputs.append(out)
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def forward(self, x, states):
        self._counter += 1
        return super().forward(x, states)


class HybridRecurrentCell(RecurrentCell):
    pass


class RNNCell(HybridRecurrentCell):
    def __init__(self, hidden_size, activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight", shape=(hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight", shape=(hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        prev = states[0] if isinstance(states, (list, tuple)) else states
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=self._hidden_size)
        h2h = F.FullyConnected(prev, h2h_weight, h2h_bias, num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(HybridRecurrentCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        ng = 4
        self.i2h_weight = self.params.get("i2h_weight", shape=(ng * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight", shape=(ng * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(ng * hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(ng * hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        h_prev, c_prev = states
        nh = self._hidden_size
        gates = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=4 * nh) + \
            F.FullyConnected(h_prev, h2h_weight, h2h_bias, num_hidden=4 * nh)
        parts = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.sigmoid(parts[0])
        f = F.sigmoid(parts[1])
        g = F.tanh(parts[2])
        o = F.sigmoid(parts[3])
        c = f * c_prev + i * g
        h = o * F.tanh(c)
        return h, [h, c]


class GRUCell(HybridRecurrentCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._hidden_size = hidden_size
        ng = 3
        self.i2h_weight = self.params.get("i2h_weight", shape=(ng * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight", shape=(ng * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(ng * hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(ng * hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        prev = states[0] if isinstance(states, (list, tuple)) else states
        nh = self._hidden_size
        gx = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=3 * nh)
        gh = F.FullyConnected(prev, h2h_weight, h2h_bias, num_hidden=3 * nh)
        xp = F.SliceChannel(gx, num_outputs=3, axis=1)
        hp = F.SliceChannel(gh, num_outputs=3, axis=1)
        r = F.sigmoid(xp[0] + hp[0])
        z = F.sigmoid(xp[1] + hp[1])
        n = F.tanh(xp[2] + r * hp[2])
        h = (1 - z) * n + z * prev
        return h, [h]


class SequentialRNNCell(RecurrentCell):
    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        out = []
        for c in self._children.values():
            out.extend(c.state_info(batch_size))
        return out

    def begin_state(self, batch_size=0, func=None, ctx=None, **kwargs):
        out = []
        for c in self._children.values():
            out.extend(c.begin_state(batch_size, func, ctx=ctx, **kwargs))
        return out

    def forward(self, x, states):
        next_states = []
        p = 0
        for c in self._children.values():
            n = len(c.state_info())
            x, s = c(x, states[p:p + n])
            next_states.extend(s)
            p += n
        return x, next_states

    def __len__(self):
        return len(self._children)


class DropoutCell(HybridRecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, x, states):
        from ... import autograd
        if self._rate > 0:
            x = F.Dropout(x, p=self._rate, axes=self._axes,
                          training=autograd.is_training() or autograd.is_recording())
        return x, states


class ModifierCell(HybridRecurrentCell):
    def __init__(self, base_cell):
        super().__init__()
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, ctx=None, **kwargs):
        return self.base_cell.begin_state(batch_size, func, ctx=ctx, **kwargs)


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def hybrid_forward(self, F, x, states):
        from ... import autograd
        out, next_states = self.base_cell(x, states)
        if not (autograd.is_training() or autograd.is_recording()):
            return out, next_states
        from ... import ndarray as nd
        po = self._prev_output if self._prev_output is not None else out * 0
        if self.zoneout_outputs > 0:
            mask = nd.random.bernoulli(self.zoneout_outputs, out.shape, ctx=out.ctx)
            out = mask * po + (1 - mask) * out
        if self.zoneout_states > 0:
            blended = []
            for s_new, s_old in zip(next_states, states):
                mask = nd.random.bernoulli(self.zoneout_states, s_new.shape, ctx=s_new.ctx)
                blended.append(mask * s_old + (1 - mask) * s_new)
            next_states = blended
        self._prev_output = out
        return out, next_states


class ResidualCell(ModifierCell):
    def hybrid_forward(self, F, x, states):
        out, next_states = self.base_cell(x, states)
        return out + x, next_states


class BidirectionalCell(HybridRecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__()
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return self.l_cell.state_info(batch_size) + self.r_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, ctx=None, **kwargs):
        return (self.l_cell.begin_state(batch_size, func, ctx=ctx, **kwargs) +
                self.r_cell.begin_state(batch_size, func, ctx=ctx, **kwargs))

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as nd
        axis = layout.find("T")
        if isinstance(inputs, NDArray):
            seq = nd.SliceChannel(inputs, num_outputs=length, axis=axis,
                                  squeeze_axis=True)
            inputs = list(seq) if isinstance(seq, list) else [seq]
        batch = inputs[0].shape[0]
        nl = len(self.l_cell.state_info())
        states = begin_state or self.begin_state(batch, ctx=inputs[0].ctx,
                                                 dtype=inputs[0].dtype)
        l_states, r_states = states[:nl], states[nl:]
        l_out, l_states = self.l_cell.unroll(length, inputs, l_states, layout, False)
        r_out, r_states = self.r_cell.unroll(length, list(reversed(inputs)),
                                             r_states, layout, False)
        outputs = [nd.Concat(lo, ro, dim=1)
                   for lo, ro in zip(l_out, reversed(r_out))]
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, l_states + r_states


class HybridSequentialRNNCell(SequentialRNNCell):
    """Hybridizable sequential cell container (reference rnn_cell.py
    HybridSequentialRNNCell). The cell chain here is jit-traced through
    the same registry path either way, so the hybrid variant shares
    SequentialRNNCell's implementation — the class exists for API parity
    and isinstance checks."""
