"""gluon.Trainer (reference python/mxnet/gluon/trainer.py:28).

Eager training driver: applies an Optimizer to a ParameterDict, optionally
through a KVStore (push/pull facade). On TPU the heavy path is
`mxnet_tpu.parallel.DataParallelTrainer` which fuses forward+backward+
allreduce+update into one jitted step; this class keeps the reference's
imperative semantics for flexibility and parity.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..base import MXNetError
from .. import engine as _engine
from ..engine import async_feed as _feed
from .. import optimizer as opt_mod
from .. import kvstore as kvs_mod
from .. import telemetry as _telem
from .parameter import Parameter, ParameterDict


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a ParameterDict/dict/list of Parameter")
        self._params: List[Parameter] = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._param2idx[p.name] = i
            self._params.append(p)
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._compression_params = compression_params
        self._kvstore_str = kvstore
        self._kvstore: Optional[kvs_mod.KVStore] = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._params_to_init: List[Parameter] = []
        self._contains_sparse_weight = False
        # bounded in-flight dispatch: the eager loop's updates are async
        # jax dispatches; the window back-pressures on the (i-K)th step's
        # updated weights so dispatch can run up to MXNET_TPU_INFLIGHT_STEPS
        # ahead without queueing unboundedly (engine/async_feed)
        self._window = _feed.DispatchWindow(name="trainer")

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and set(optimizer_params) - {"rescale_grad"}:
                raise MXNetError("optimizer_params must be None when optimizer "
                                 "is an Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer, param_dict=param_dict,
                                             **optimizer_params)
        self._updaters = [opt_mod.get_updater(self._optimizer)]

    def _init_kvstore(self):
        if self._kvstore_str:
            kv = kvs_mod.create(self._kvstore_str) if isinstance(self._kvstore_str, str) \
                else self._kvstore_str
            self._kvstore = kv
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            update_on_kv = self._update_on_kvstore
            if update_on_kv is None:
                update_on_kv = kv.type.startswith("dist")
            self._update_on_kvstore_flag = update_on_kv
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    kv.init(i, p.data())
            if update_on_kv:
                kv.set_optimizer(self._optimizer)
        else:
            self._kvstore = None
            self._update_on_kvstore_flag = False
        self._kv_initialized = True

    # -- properties ----------------------------------------------------------
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # -- step ------------------------------------------------------------------
    @property
    def donation_active(self) -> bool:
        """True when the update kernels alias weight/optimizer-state buffers
        in place (engine.donation_enabled(); TPU/GPU backends)."""
        return _engine.donation_enabled()

    def step(self, batch_size, ignore_stale_grad=False):
        """rescale grads by 1/batch_size, allreduce, update (reference
        trainer.py:320). The per-param updates run through the donated
        optimizer kernels, so on backends with input-output aliasing each
        weight/state buffer is updated in place; step timing lands in the
        profiler's aggregate table while a profile is running."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        from .. import profiler as _profiler
        t0 = time.perf_counter() if _profiler._state["running"] else None
        self._allreduce_grads()
        self._update(ignore_stale_grad)
        # admit this step's last updated weight into the in-flight window:
        # per-device dispatch order means its readiness implies every
        # earlier-dispatched update of this step completed too
        h = None
        for p in reversed(self._params):
            if p.grad_req != "null":
                h = p.data()._data
                break
        if h is not None:
            self._window.admit(h)
        if t0 is not None:
            _profiler._record("trainer.step", "trainer", t0,
                              time.perf_counter())
        if _telem._ENABLED:
            # roofline ledger: the eager allreduce+update slice gets its own
            # region, so interval pacing attributes the optimizer's wall
            # time here instead of blaming the NEXT forward region for it
            _engine.record_execution(
                "step", 0.0,
                region=f"trainer.update[{type(self._optimizer).__name__}]")
            # step() is the once-per-iteration sync point: the inter-step
            # interval telemetry derives here covers the WHOLE eager loop
            # (forward + backward + update), and the engine's executed-FLOPs
            # delta over the same window yields the MFU estimate
            _telem.record_step(batch_size, source="trainer",
                               lr=float(self._optimizer.learning_rate))

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        if not self._update_on_kvstore_flag:
            live = [(i, p) for i, p in enumerate(self._params)
                    if p.grad_req != "null"]
            if len(live) > 1 and (self._kvstore.type.startswith("dist")
                                  or self._kvstore.type in ("tpu", "nccl")):
                # grads ride the kvstore's bucketed reduce path
                # (parallel/zero.py fusion buckets — one collective per
                # bucket instead of one per key), but one pushpull over ALL
                # keys can only be issued after the whole backward. Plan the
                # same buckets here and issue one pushpull per bucket in
                # reverse declaration order — the order backward finalizes
                # gradients — so each bucket's collective dispatches while
                # earlier-declared grads are still being produced. The
                # reduced values land in the same grad buffers either way.
                from ..parallel import zero as _zero
                from ..base import env as _env
                grad_of = {i: p.grad() for i, p in live}
                entries = [(i, grad_of[i].shape, grad_of[i].dtype)
                           for i, _ in live]
                buckets = _zero.plan_buckets(
                    entries, 1, int(_env.get("MXNET_TPU_BUCKET_BYTES")))
                for b in sorted(buckets, key=lambda b: -max(b.indices)):
                    grads = [grad_of[i] for i in b.indices]
                    self._kvstore.pushpull(list(b.indices), grads,
                                           out=grads)
                return
            for i, p in live:
                self._kvstore.push(i, p.grad())
            return
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            # weights live on the store: fused pushpull applies update there
            self._kvstore.pushpull(i, p.grad(), out=p.data())

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._kvstore is not None and self._update_on_kvstore_flag:
            return  # already applied in pushpull
        updater = self._updaters[0]
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            updater(i, p.grad(), p.data())

    def zero_grad(self):
        for p in self._params:
            p.zero_grad()

    def drain(self):
        """Block until every dispatched step's updates completed (epoch /
        checkpoint boundary drain point)."""
        self._window.drain()

    # -- states ----------------------------------------------------------------
    def state_dict(self):
        """Schedule counters the legacy save_states path drops: optimizer
        num_update / per-index update counts / mutable lr-scheduler fields
        and the grad rescale. Elastic snapshots carry this so a resumed
        eager loop sees the same lr at step K+1 (elastic/state.py)."""
        from ..elastic import state as _estate
        return {"sched": _estate.sched_state(self._optimizer),
                "scale": self._scale}

    def load_state_dict(self, d):
        from ..elastic import state as _estate
        if d.get("sched"):
            _estate.install_sched(self._optimizer, d["sched"])
        if "scale" in d:
            self._scale = float(d["scale"])

    def save_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is not None and self._update_on_kvstore_flag:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as f:
                f.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is not None and self._update_on_kvstore_flag:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updaters[0].set_states(f.read())
            self._optimizer = self._updaters[0].optimizer
