"""gluon.utils (reference python/mxnet/gluon/utils.py)."""
from __future__ import annotations

from typing import List

from ..base import MXNetError
from ..context import Context
from ..ndarray import NDArray


def split_data(data: NDArray, num_slice: int, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data of shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list: List[Context], batch_axis=0, even_split=True):
    """Slice batch across contexts (reference utils.py split_and_load). On a
    one-chip host this is the identity; across a mesh prefer the fused
    parallel path."""
    from ..ndarray import array
    if not isinstance(data, NDArray):
        data = array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def _warn_if_not_finite(total):
    """Designed sync point for clip_global_norm(check_isfinite=True): the
    finiteness read is the ONE host transfer, isolated off the hot path."""
    import jax.numpy as jnp
    if not bool(jnp.isfinite(total)):
        import warnings
        warnings.warn("nan or inf in clip_global_norm")


def clip_global_norm(arrays: List[NDArray], max_norm: float,
                     check_isfinite=True):
    """reference utils.py clip_global_norm — TPU-native: the norm and the
    scale stay on device and the rescale applies unconditionally
    (``min(1, max_norm/total)`` is the identity when under the norm), so
    per-step clipping never blocks the dispatch queue. Pass
    check_isfinite=False to skip the host finiteness read entirely; the
    returned total is a device scalar that only syncs if inspected."""
    import jax.numpy as jnp
    total = jnp.sqrt(sum(jnp.sum(jnp.square(a._data.astype(jnp.float32)))
                         for a in arrays))
    scale = jnp.minimum(1.0, max_norm / (total + 1e-8))
    # non-finite norm: leave the arrays untouched (the reference's
    # `scale < 1.0` host branch was False for NaN), computed on device
    scale = jnp.where(jnp.isfinite(scale), scale, 1.0)
    for a in arrays:
        a._set_data(a._data * scale.astype(a._data.dtype))
    if check_isfinite:
        _warn_if_not_finite(total)
    return total


def check_sha1(filename, sha1_hash):
    import hashlib
    h = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            d = f.read(1048576)
            if not d:
                break
            h.update(d)
    return h.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    raise MXNetError("network egress is disabled in this environment; place "
                     "files locally and pass their path")


def shape_is_known(shape):
    if shape is None:
        return False
    return all(s > 0 for s in shape)
