"""Core layers (reference python/mxnet/gluon/nn/basic_layers.py:144-700)."""
from __future__ import annotations

from typing import Optional

import numpy as _np

from ...base import MXNetError
from ..block import Block, HybridBlock, defer_aux_update
from ..parameter import Parameter


class Sequential(Block):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def forward(self, x):
        for b in self._children.values():
            x = b(x)
        return x

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def hybrid_forward(self, F, x):
        for b in self._children.values():
            x = b(x)
        return x

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class Dense(HybridBlock):
    """reference basic_layers.py:144 — weight (units, in_units)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._units = units
        self._flatten = flatten
        self._activation = activation
        self._use_bias = use_bias
        self.weight = self.params.get("weight", shape=(units, in_units),
                                      dtype=dtype, init=weight_initializer,
                                      allow_deferred_init=True)
        if use_bias:
            self.bias = self.params.get("bias", shape=(units,), dtype=dtype,
                                        init=bias_initializer,
                                        allow_deferred_init=True)
        else:
            self.bias = None

    def infer_shape(self, x, *args):
        in_units = int(_np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=(bias is None), flatten=self._flatten)
        if self._activation:
            out = F.Activation(out, act_type=self._activation)
        return out


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        self._rate = rate
        self._axes = tuple(axes)

    def hybrid_forward(self, F, x):
        from ... import autograd
        return F.Dropout(x, p=self._rate, axes=self._axes,
                         training=autograd.is_training() or autograd.is_recording())


class BatchNorm(HybridBlock):
    """reference basic_layers.py:282 — running stats updated via
    defer_aux_update (functional under traces)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix, params)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.gamma = self.params.get("gamma", shape=(in_channels,), init=gamma_initializer,
                                     allow_deferred_init=True,
                                     grad_req="write" if scale else "null")
        self.beta = self.params.get("beta", shape=(in_channels,), init=beta_initializer,
                                    allow_deferred_init=True,
                                    grad_req="write" if center else "null")
        self.running_mean = self.params.get("running_mean", shape=(in_channels,),
                                            init=running_mean_initializer,
                                            allow_deferred_init=True, grad_req="null",
                                            differentiable=False)
        self.running_var = self.params.get("running_var", shape=(in_channels,),
                                           init=running_variance_initializer,
                                           allow_deferred_init=True, grad_req="null",
                                           differentiable=False)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def cast(self, dtype):
        # keep stats in f32 (TPU numerics)
        import jax.numpy as jnp
        if jnp.dtype(dtype) in (jnp.float16, jnp.bfloat16):
            dtype = "float32"
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd
        training = (autograd.is_training() or autograd.is_recording()) \
            and not self._use_global_stats
        out, mean, var = F.BatchNorm(
            x, gamma, beta, running_mean, running_var, eps=self._epsilon,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis,
            training=training)
        if training:
            m = self._momentum
            defer_aux_update(self.running_mean,
                             m * running_mean._data + (1 - m) * mean._data)
            defer_aux_update(self.running_var,
                             m * running_var._data + (1 - m) * var._data)
        return out


class SyncBatchNorm(BatchNorm):
    """Cross-device BN (reference contrib sync_batch_norm). On TPU the batch
    axis is sharded by the mesh; under pjit/shard_map the mean/var reductions
    become cross-replica automatically, so this is BatchNorm + a note."""

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        kwargs.setdefault("prefix", None)
        super().__init__(in_channels=in_channels, **kwargs)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None, params=None):
        super().__init__(prefix, params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        # sparse_grad marks the weight's gradient row_sparse so optimizers
        # with lazy_update skip rows absent from the batch (reference
        # gluon/nn/basic_layers.py Embedding(sparse_grad=True))
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer,
            grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class Activation(HybridBlock):
    def __init__(self, activation, prefix=None, params=None):
        super().__init__(prefix, params)
        self._act_type = activation

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, prefix=None, params=None):
        super().__init__(prefix, params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, in_channels=1, prefix=None, params=None):
        super().__init__(prefix, params)
        from ... import initializer
        self.alpha = self.params.get("alpha", shape=(in_channels,),
                                     init=alpha_initializer or initializer.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        a = alpha.reshape((1, -1) + (1,) * max(x.ndim - 2, 0)) if x.ndim > 1 else alpha
        return F.broadcast_maximum(x, x * 0) + F.broadcast_minimum(x, x * 0) * a


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, approximate=False, prefix=None, params=None):
        super().__init__(prefix, params)
        self._approximate = approximate

    def hybrid_forward(self, F, x):
        return F.gelu(x, approximate=self._approximate)


class Swish(HybridBlock):
    def __init__(self, beta=1.0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class LayerNorm(HybridBlock):
    """reference basic_layers.py:546."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer, allow_deferred_init=True,
                                     grad_req="write" if scale else "null")
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer, allow_deferred_init=True,
                                    grad_req="write" if center else "null")

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    """reference basic_layers.py:630."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer, allow_deferred_init=True,
                                     grad_req="write" if scale else "null")
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer, allow_deferred_init=True,
                                    grad_req="write" if center else "null")

    def infer_shape(self, x, *args):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._epsilon = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer, allow_deferred_init=True)
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix)
        if isinstance(function, str):
            from ... import ndarray as nd_mod
            function = getattr(nd_mod, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix)
        self._func_name = function if isinstance(function, str) else function.__name__
        self._func = function

    def hybrid_forward(self, F, *args):
        if isinstance(self._func, str):
            return getattr(F, self._func)(*args)
        return self._func(F, *args)


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x
