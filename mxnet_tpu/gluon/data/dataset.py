"""Datasets (reference python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

import numpy as _np

from ...ndarray import NDArray, array


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([self[i] for i in range(len(self)) if fn(self[i])])

    def shard(self, num_shards, index):
        items = [self[i] for i in range(index, len(self), num_shards)]
        return SimpleDataset(items)

    def take(self, count):
        return SimpleDataset([self[i] for i in range(min(count, len(self)))])

    def transform(self, fn, lazy=True):
        return _LazyTransformDataset(self, fn)

    def transform_first(self, fn, lazy=True):
        def first(*items):
            if len(items) == 1:
                return fn(items[0])
            return (fn(items[0]),) + items[1:]
        return self.transform(first, lazy)


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for a in args:
            if isinstance(a, NDArray):
                a = a.asnumpy()
            assert len(a) == self._length
            self._data.append(a)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over an indexed RecordIO file (reference record dataset)."""

    def __init__(self, filename):
        from ...recordio import MXIndexedRecordIO
        self._record = MXIndexedRecordIO(filename[:-4] + ".idx", filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
