"""DataLoader (reference python/mxnet/gluon/data/dataloader.py:28-102).

The reference forks worker processes sharing NDArrays through POSIX shm
(cpu_shared_storage_manager). Forking is hostile to a live PJRT/TPU client,
so workers here are threads running the numpy-side of the pipeline (decode/
augment release the GIL in numpy/PIL), with batches staged host-side and
device_put once per batch — the same overlap the reference's PrefetcherIter
provides.
"""
from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as _np

from ...ndarray import NDArray, array
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, SequentialSampler, Sampler


def default_batchify_fn(data):
    """Stack samples into a batch (reference dataloader.py default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp
        return NDArray(jnp.stack([d._data for d in data]), data[0].ctx)
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    arr = _np.asarray(data)
    if arr.dtype == _np.float64:
        arr = arr.astype(_np.float32)
    return array(arr)


def default_mp_batchify_fn(data):
    return default_batchify_fn(data)


class DataLoader:
    def __init__(self, dataset: Dataset, batch_size=None, shuffle=False,
                 sampler: Optional[Sampler] = None, last_batch=None,
                 batch_sampler=None, batchify_fn=None, num_workers=0,
                 pin_memory=False, pin_device_id=0, prefetch=None,
                 thread_pool=False, timeout=120):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._timeout = timeout

    def __len__(self):
        return len(self._batch_sampler)

    def device_feed(self, sharding=None, mesh=None, data_spec=None,
                    depth=None, trainer=None):
        """Wrap this loader in an ``engine.async_feed.DeviceFeed``: a
        background producer runs the batchify pipeline AND the explicit
        ``jax.device_put`` (replicated, or sharded per ``mesh``+
        ``data_spec`` / a ``DataParallelTrainer`` via ``trainer=``), so
        H2D transfer overlaps step compute (docs/input_pipeline.md)."""
        from ...engine.async_feed import DeviceFeed
        if trainer is not None:
            return DeviceFeed.for_trainer(self, trainer, depth=depth)
        return DeviceFeed(self, sharding=sharding, mesh=mesh,
                          data_spec=data_spec, depth=depth)

    def __iter__(self):
        if self._num_workers == 0:
            for batch_idx in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in batch_idx])
            return
        yield from self._threaded_iter()

    def _threaded_iter(self):
        batches = list(self._batch_sampler)
        out_q: "queue.Queue" = queue.Queue()
        n_batches = len(batches)
        task_q: "queue.Queue" = queue.Queue()
        results = {}
        for i, b in enumerate(batches):
            task_q.put((i, b))

        def worker():
            while True:
                try:
                    i, idx = task_q.get_nowait()
                except queue.Empty:
                    return
                try:
                    samples = [self._dataset[j] for j in idx]
                    out_q.put((i, self._batchify_fn(samples)))
                except Exception as e:
                    out_q.put((i, e))

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        next_out = 0
        received = 0
        while next_out < n_batches:
            while next_out not in results:
                i, payload = out_q.get(timeout=self._timeout)
                results[i] = payload
                received += 1
            payload = results.pop(next_out)
            next_out += 1
            if isinstance(payload, Exception):
                raise payload
            yield payload
