"""Shared helpers for vision datasets."""


class SyntheticMixin:
    pass
