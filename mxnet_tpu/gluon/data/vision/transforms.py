"""Vision transforms (reference python/mxnet/gluon/data/vision/transforms.py +
src/operator/image/*). Numpy/host-side: transforms run in DataLoader workers
on uint8 arrays before device_put — keeping the TPU free for the model."""
from __future__ import annotations

import numpy as _np

from .... import random as _mxrand
from ....ndarray import NDArray, array
from ...block import Block, HybridBlock
from ...nn import Sequential


class Compose(Sequential):
    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        if isinstance(x, NDArray):
            return x.astype(self._dtype)
        return _np.asarray(x, dtype=self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def forward(self, x):
        a = x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)
        a = a.astype(_np.float32) / 255.0
        if a.ndim == 3:
            a = a.transpose(2, 0, 1)
        elif a.ndim == 4:
            a = a.transpose(0, 3, 1, 2)
        return a


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, dtype=_np.float32)
        self._std = _np.asarray(std, dtype=_np.float32)

    def forward(self, x):
        a = x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x, dtype=_np.float32)
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return (a - mean) / std


def _resize_np(a, size):
    """Nearest-neighbor host resize (OpenCV-free)."""
    h, w = a.shape[:2]
    oh, ow = (size, size) if isinstance(size, int) else (size[1], size[0])
    ri = (_np.arange(oh) * h / oh).astype(int).clip(0, h - 1)
    ci = (_np.arange(ow) * w / ow).astype(int).clip(0, w - 1)
    return a[ri][:, ci]


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size

    def forward(self, x):
        a = x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)
        return _resize_np(a, self._size)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        a = x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)
        ow, oh = self._size
        h, w = a.shape[:2]
        if h < oh or w < ow:
            a = _resize_np(a, (max(ow, w), max(oh, h)))
            h, w = a.shape[:2]
        y0 = (h - oh) // 2
        x0 = (w - ow) // 2
        return a[y0:y0 + oh, x0:x0 + ow]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3), interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        a = x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = _mxrand.host_rng().uniform(*self._scale) * area
            ar = _np.exp(_mxrand.host_rng().uniform(_np.log(self._ratio[0]), _np.log(self._ratio[1])))
            nw = int(round(_np.sqrt(target_area * ar)))
            nh = int(round(_np.sqrt(target_area / ar)))
            if nw <= w and nh <= h:
                x0 = _mxrand.host_rng().randint(0, w - nw + 1)
                y0 = _mxrand.host_rng().randint(0, h - nh + 1)
                crop = a[y0:y0 + nh, x0:x0 + nw]
                return _resize_np(crop, self._size)
        return _resize_np(a, self._size)


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._pad = pad

    def forward(self, x):
        a = x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)
        if self._pad:
            p = self._pad
            a = _np.pad(a, ((p, p), (p, p), (0, 0)), mode="constant")
        h, w = a.shape[:2]
        ow, oh = self._size
        y0 = _mxrand.host_rng().randint(0, max(h - oh, 0) + 1)
        x0 = _mxrand.host_rng().randint(0, max(w - ow, 0) + 1)
        return a[y0:y0 + oh, x0:x0 + ow]


class RandomFlipLeftRight(Block):
    def forward(self, x):
        a = x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)
        if _mxrand.host_rng().rand() < 0.5:
            a = a[:, ::-1].copy()
        return a


class RandomFlipTopBottom(Block):
    def forward(self, x):
        a = x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)
        if _mxrand.host_rng().rand() < 0.5:
            a = a[::-1].copy()
        return a


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        a = _np.asarray(x, dtype=_np.float32)
        f = 1.0 + _mxrand.host_rng().uniform(-self._b, self._b)
        return _np.clip(a * f, 0, 255)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        a = _np.asarray(x, dtype=_np.float32)
        f = 1.0 + _mxrand.host_rng().uniform(-self._c, self._c)
        mean = a.mean()
        return _np.clip((a - mean) * f + mean, 0, 255)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        a = _np.asarray(x, dtype=_np.float32)
        f = 1.0 + _mxrand.host_rng().uniform(-self._s, self._s)
        gray = a.mean(axis=-1, keepdims=True)
        return _np.clip(gray + (a - gray) * f, 0, 255)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        for t in self._ts:
            x = t(x)
        return x


class RandomHue(Block):
    """Random hue jitter (reference transforms.py RandomHue): rotate RGB
    around the luminance axis by the YIQ hue-rotation matrix."""

    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        a = _np.asarray(x, dtype=_np.float32)
        f = _mxrand.host_rng().uniform(-self._h, self._h)
        theta = f * _np.pi
        u, w = _np.cos(theta), _np.sin(theta)
        t_yiq = _np.array([[0.299, 0.587, 0.114],
                           [0.596, -0.274, -0.321],
                           [0.211, -0.523, 0.311]], _np.float32)
        t_rgb = _np.array([[1.0, 0.956, 0.621],
                           [1.0, -0.272, -0.647],
                           [1.0, -1.107, 1.705]], _np.float32)
        rot = _np.diag(_np.array([1.0, u, u], _np.float32))
        rot[1, 2] = -w
        rot[2, 1] = w
        m = t_rgb @ rot @ t_yiq
        return _np.clip(a @ m.T, 0, 255)


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (reference transforms.py
    RandomLighting)."""

    _EIGVAL = _np.array([55.46, 4.794, 1.148], _np.float32)
    _EIGVEC = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], _np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        a = _np.asarray(x, dtype=_np.float32)
        alpha = _mxrand.host_rng().normal(0, self._alpha, 3).astype(_np.float32)
        shift = self._EIGVEC @ (alpha * self._EIGVAL)
        return _np.clip(a + shift, 0, 255)


class CropResize(Block):
    """Fixed crop then resize (reference transforms.py CropResize)."""

    def __init__(self, x, y, width, height, size=None, interpolation=1):
        super().__init__()
        self._box = (x, y, width, height)
        self._size = size
        self._interp = interpolation

    def forward(self, img):
        from .... import image as _img
        x0, y0, w, h = self._box
        a = _np.asarray(img)
        out = a[y0:y0 + h, x0:x0 + w]
        if self._size:
            sz = self._size if isinstance(self._size, (tuple, list)) \
                else (self._size, self._size)
            out = _np.asarray(_img.imresize(
                array(out), sz[0], sz[1], interp=self._interp).asnumpy())
        return out
