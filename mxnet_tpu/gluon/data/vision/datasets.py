"""Vision datasets (reference python/mxnet/gluon/data/vision/datasets.py).

Zero-egress environment: when the canonical files are absent under `root`,
datasets fall back to deterministic synthetic data with the right shapes and
label structure so examples/tests run hermetically.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as _np

from .dataset_utils import SyntheticMixin
from ..dataset import Dataset


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        x = self._data[idx]
        y = self._label[idx]
        if self._transform is not None:
            return self._transform(x, y)
        return x, y


class MNIST(_DownloadedDataset):
    """MNIST; synthetic fallback (28x28x1 uint8, 10 classes)."""

    _shape = (28, 28, 1)
    _classes = 10
    _files = {True: ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
              False: ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")}

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None, synthetic_size=2048):
        self._synthetic_size = synthetic_size
        super().__init__(root, train, transform)

    def _get_data(self):
        img_f, lab_f = self._files[self._train]
        img_p = os.path.join(self._root, img_f)
        lab_p = os.path.join(self._root, lab_f)
        if os.path.exists(img_p) and os.path.exists(lab_p):
            with gzip.open(lab_p, "rb") as f:
                struct.unpack(">II", f.read(8))
                label = _np.frombuffer(f.read(), dtype=_np.uint8).astype(_np.int32)
            with gzip.open(img_p, "rb") as f:
                _, n, r, c = struct.unpack(">IIII", f.read(16))
                data = _np.frombuffer(f.read(), dtype=_np.uint8).reshape(n, r, c, 1)
        else:
            rng = _np.random.RandomState(42 if self._train else 43)
            n = self._synthetic_size
            label = rng.randint(0, self._classes, n).astype(_np.int32)
            base = rng.rand(self._classes, *self._shape)
            data = ((base[label] * 0.6 + rng.rand(n, *self._shape) * 0.4) * 255) \
                .astype(_np.uint8)
        self._data = data
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "fashion-mnist"),
                 train=True, transform=None, synthetic_size=2048):
        super().__init__(root, train, transform, synthetic_size)


class CIFAR10(_DownloadedDataset):
    _shape = (32, 32, 3)
    _classes = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None, synthetic_size=2048):
        self._synthetic_size = synthetic_size
        super().__init__(root, train, transform)

    def _get_data(self):
        files = [os.path.join(self._root, f"data_batch_{i}.bin") for i in range(1, 6)] \
            if self._train else [os.path.join(self._root, "test_batch.bin")]
        if all(os.path.exists(f) for f in files):
            datas, labels = [], []
            for fn in files:
                raw = _np.fromfile(fn, dtype=_np.uint8).reshape(-1, 3073)
                labels.append(raw[:, 0].astype(_np.int32))
                datas.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            self._data = _np.concatenate(datas)
            self._label = _np.concatenate(labels)
        else:
            rng = _np.random.RandomState(44 if self._train else 45)
            n = self._synthetic_size
            self._label = rng.randint(0, self._classes, n).astype(_np.int32)
            base = rng.rand(self._classes, *self._shape)
            self._data = ((base[self._label] * 0.6 +
                           rng.rand(n, *self._shape) * 0.4) * 255).astype(_np.uint8)


class CIFAR100(CIFAR10):
    _classes = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=False, train=True, transform=None, synthetic_size=2048):
        super().__init__(root, train, transform, synthetic_size)


class ImageFolderDataset(Dataset):
    """folder/label_name/image.jpg layout (reference ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fn in sorted(os.listdir(path)):
                if fn.lower().endswith((".jpg", ".jpeg", ".png", ".npy")):
                    self.items.append((os.path.join(path, fn), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        fn, label = self.items[idx]
        if fn.endswith(".npy"):
            img = _np.load(fn)
        else:
            from ....image import imread
            img = imread(fn).asnumpy()
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageRecordDataset(Dataset):
    """Images + labels from an indexed RecordIO file (reference
    gluon/data/vision/datasets.py:233 — each record is a packed header
    with the label followed by the encoded image)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._rec = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._rec)

    def __getitem__(self, idx):
        from ....recordio import unpack
        from .... import image as _image
        header, buf = unpack(self._rec[idx])
        # image.imdecode, not unpack_img: RGB output like every other
        # decode path here (+ the PIL fallback on cv2-less hosts)
        data = _image.imdecode(buf, flag=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(data, label)
        return data, label
