"""gluon.contrib (reference python/mxnet/gluon/contrib/): Estimator
train-loop, extra nn blocks, rnn extras."""
from . import estimator
from . import nn
from .estimator import Estimator
