"""Estimator event handlers (reference
gluon/contrib/estimator/event_handler.py)."""
from __future__ import annotations

import logging
import os
import time


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop after max_epoch/max_batch (reference StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True
        return self.stop_training

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True
        return self.stop_training


class MetricHandler(EpochBegin, BatchEnd):
    """Resets/updates train metrics (reference MetricHandler)."""

    def __init__(self, train_metrics):
        self.train_metrics = train_metrics or []

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.train_metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.train_metrics:
            from ....metric import Loss as LossMetric
            if isinstance(m, LossMetric):
                m.update(0, loss)
            else:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Runs validation every epoch/N batches (reference ValidationHandler)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0
        self.priority = priority

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    """(reference LoggingHandler)"""

    def __init__(self, log_interval="epoch", metrics=None, priority=float("inf")):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.batch_index = 0
        self.current_epoch = 0
        self.priority = priority
        self.processed_samples = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        logging.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        logging.info("Training finished in %.3fs", time.time() - self.train_start)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()

    def epoch_end(self, estimator, *args, **kwargs):
        msg = f"Epoch[{self.current_epoch}] finished in " \
              f"{time.time() - self.epoch_start:.3f}s: "
        for m in self.metrics:
            name, value = m.get()
            msg += f"{name}: {value:.4f} "
        logging.info(msg)
        self.current_epoch += 1
        self.batch_index = 0

    def batch_end(self, estimator, *args, **kwargs):
        if isinstance(self.log_interval, int) and \
                self.batch_index % self.log_interval == 0:
            msg = f"[Epoch {self.current_epoch}][Batch {self.batch_index}] "
            for m in self.metrics:
                name, value = m.get()
                msg += f"{name}: {value:.4f} "
            logging.info(msg)
        self.batch_index += 1


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save params every epoch; track best by monitored metric
    (reference CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_epoch = 0
        self.current_batch = 0
        self.best = None
        self.mode = mode
        os.makedirs(model_dir, exist_ok=True)

    def _improved(self, value):
        if self.best is None:
            return True
        if self.mode == "min":
            return value < self.best
        if self.mode == "max":
            return value > self.best
        name = self.monitor.get()[0] if self.monitor else ""
        lower_better = any(k in name.lower() for k in ("loss", "error", "mse",
                                                       "mae", "perplexity"))
        return value < self.best if lower_better else value > self.best

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            path = os.path.join(self.model_dir,
                                f"{self.model_prefix}-epoch{self.current_epoch}.params")
            estimator.net.save_parameters(path)
            if self.save_best and self.monitor is not None:
                value = self.monitor.get()[1]
                if self._improved(value):
                    self.best = value
                    best_path = os.path.join(self.model_dir,
                                             f"{self.model_prefix}-best.params")
                    estimator.net.save_parameters(best_path)

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            path = os.path.join(self.model_dir,
                                f"{self.model_prefix}-batch{self.current_batch}.params")
            estimator.net.save_parameters(path)


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when the monitored metric stops improving
    (reference EarlyStoppingHandler)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.mode = mode
        self.baseline = baseline
        self.wait = 0
        self.best = None
        self.stop_training = False
        self.stopped_epoch = 0
        self.current_epoch = 0

    def _better(self, a, b):
        name = self.monitor.get()[0]
        lower_better = self.mode == "min" or (
            self.mode == "auto" and any(k in name.lower() for k in
                                        ("loss", "error", "mse", "mae")))
        return (a < b - self.min_delta) if lower_better \
            else (a > b + self.min_delta)

    def epoch_end(self, estimator, *args, **kwargs):
        value = self.monitor.get()[1]
        if self.best is None or self._better(value, self.best):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                self.stopped_epoch = self.current_epoch
        self.current_epoch += 1
        return self.stop_training

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch:
            logging.info("Early stopping at epoch %d", self.stopped_epoch)
