"""Estimator (reference gluon/contrib/estimator/estimator.py): the
batteries-included gluon fit loop — autograd record, loss, Trainer step,
metric updates, event handlers."""
from __future__ import annotations

import logging
from typing import List, Optional, Union

from ....base import MXNetError
from .... import autograd
from ....metric import EvalMetric, Loss as LossMetric, create as metric_create
from ...loss import Loss as GluonLoss
from ...trainer import Trainer
from .event_handler import (TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                            BatchBegin, BatchEnd, StoppingHandler,
                            MetricHandler, LoggingHandler, ValidationHandler)


class Estimator:
    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 initializer=None, trainer=None, context=None,
                 val_loss=None):
        self.net = net
        if not isinstance(loss, GluonLoss):
            raise MXNetError("loss must be a gluon Loss")
        self.loss = loss
        self.train_metrics = self._check_metrics(train_metrics)
        self.val_metrics = self._check_metrics(val_metrics)
        self.context = context
        if initializer is not None:
            self.net.initialize(initializer, ctx=context, force_reinit=True)
        self.trainer = trainer or Trainer(
            net.collect_params(), "sgd", {"learning_rate": 0.001})
        self.train_loss_metric = LossMetric(name="train_loss")
        self.val_loss_metric = LossMetric(name="val_loss")
        self.stop_training = False

    @staticmethod
    def _check_metrics(metrics):
        if metrics is None:
            return []
        if isinstance(metrics, EvalMetric):
            return [metrics]
        return [m if isinstance(m, EvalMetric) else metric_create(m)
                for m in metrics]

    def evaluate_batch(self, batch):
        data, label = batch[0], batch[1]
        pred = self.net(data)
        loss = self.loss(pred, label)
        return data, label, pred, loss

    def evaluate(self, val_data):
        for m in self.val_metrics + [self.val_loss_metric]:
            m.reset()
        for batch in val_data:
            _, label, pred, loss = self.evaluate_batch(batch)
            for m in self.val_metrics:
                m.update(label, pred)
            self.val_loss_metric.update(0, loss)
        return [(m.get()) for m in self.val_metrics + [self.val_loss_metric]]

    def fit_batch(self, batch):
        data, label = batch[0], batch[1]
        with autograd.record():
            pred = self.net(data)
            loss = self.loss(pred, label)
        loss.backward()
        bs = data.shape[0]
        self.trainer.step(bs)
        return data, label, pred, loss

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None):
        if epochs is None and batches is None:
            epochs = 1
        handlers = self._prepare_handlers(val_data, epochs, batches,
                                          event_handlers)

        def call(event, **kw):
            stop = False
            for h in handlers:
                if isinstance(h, _EVENT_BASE[event]):
                    r = getattr(h, event)(self, **kw)
                    stop = stop or bool(r)
            return stop

        self.stop_training = False
        call("train_begin")
        while not self.stop_training:
            call("epoch_begin")
            for batch in train_data:
                call("batch_begin", batch=batch)
                data, label, pred, loss = self.fit_batch(batch)
                self.train_loss_metric.update(0, loss)
                if call("batch_end", batch=batch, pred=pred, label=label,
                        loss=loss):
                    self.stop_training = True
                    break
            if call("epoch_end"):
                self.stop_training = True
            if hasattr(train_data, "reset"):
                train_data.reset()
        call("train_end")

    def _prepare_handlers(self, val_data, epochs, batches, event_handlers):
        handlers = list(event_handlers or [])
        has_stopping = any(isinstance(h, StoppingHandler) for h in handlers)
        if not has_stopping:
            handlers.append(StoppingHandler(max_epoch=epochs,
                                            max_batch=batches))
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(self.train_metrics))
        if val_data is not None and \
                not any(isinstance(h, ValidationHandler) for h in handlers):
            handlers.append(ValidationHandler(val_data, self.evaluate))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(
                metrics=self.train_metrics + [self.train_loss_metric]))
        # fire in priority order (ValidationHandler=-1000 runs BEFORE user
        # handlers like EarlyStopping that read validation metrics)
        handlers.sort(key=lambda h: getattr(h, "priority", 0))
        return handlers


_EVENT_BASE = {
    "train_begin": TrainBegin,
    "train_end": TrainEnd,
    "epoch_begin": EpochBegin,
    "epoch_end": EpochEnd,
    "batch_begin": BatchBegin,
    "batch_end": BatchEnd,
}
