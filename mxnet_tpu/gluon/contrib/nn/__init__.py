"""Extra gluon blocks (reference gluon/contrib/nn/basic_layers.py):
Concurrent, HybridConcurrent, Identity, SparseEmbedding, SyncBatchNorm,
PixelShuffle."""
from .basic_layers import (Concurrent, HybridConcurrent, Identity,
                           SparseEmbedding, SyncBatchNorm, PixelShuffle2D)
