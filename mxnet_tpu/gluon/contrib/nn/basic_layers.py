"""Extra gluon blocks (reference gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ....base import MXNetError
from ...block import Block, HybridBlock
from ...nn.basic_layers import Embedding, BatchNorm


class Concurrent(Block):
    """Run children on the same input, concatenate outputs
    (reference contrib/nn/basic_layers.py Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__()
        self.axis = axis
        self._layers = []

    def add(self, *blocks):
        for b in blocks:
            self._layers.append(b)
            self.register_child(b)
        return self

    def forward(self, x):
        from .... import ndarray as nd
        outs = [b(x) for b in self._layers]
        return nd.concat(*outs, dim=self.axis)


class HybridConcurrent(HybridBlock):
    """(reference HybridConcurrent)"""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__()
        self.axis = axis
        self._layers = []

    def add(self, *blocks):
        for b in blocks:
            self._layers.append(b)
            self.register_child(b)
        return self

    def hybrid_forward(self, F, x):
        outs = [b(x) for b in self._layers]
        return F.concat(*outs, dim=self.axis)


class Identity(HybridBlock):
    """(reference Identity)"""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Embedding):
    """Embedding with row-sparse gradient semantics (reference
    SparseEmbedding). On TPU the gradient is dense (XLA scatter-add) but the
    API — including sparse_grad attribute — is preserved; pair with
    kvstore.row_sparse_pull for the sparse-update workflow."""

    def __init__(self, input_dim, output_dim, dtype="float32", **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype, **kwargs)
        self.sparse_grad = True


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference contrib sync_batch_norm.cc).

    Under pjit, batch statistics are computed over the GLOBAL batch
    automatically (XLA all-reduces the mean/var reductions over the sharded
    batch axis) — so plain BatchNorm IS sync BN in the fused step; this
    subclass exists for API parity and for explicitly choosing the number
    of synchronizing devices in eager mode (ignored on TPU)."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)
        self.num_devices = num_devices


class PixelShuffle2D(HybridBlock):
    """Sub-pixel upsampling (reference contrib PixelShuffle2D): rearranges
    (B, C*f1*f2, H, W) -> (B, C, H*f1, W*f2)."""

    def __init__(self, factor):
        super().__init__()
        if isinstance(factor, int):
            factor = (factor, factor)
        self._factors = tuple(int(f) for f in factor)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        B, C, H, W = x.shape
        c_out = C // (f1 * f2)
        x = x.reshape((B, c_out, f1, f2, H, W))
        x = x.transpose((0, 1, 4, 2, 5, 3))      # B, c, H, f1, W, f2
        return x.reshape((B, c_out, H * f1, W * f2))
