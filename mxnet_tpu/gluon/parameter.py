"""Parameter / ParameterDict (reference python/mxnet/gluon/parameter.py).

Parameters hold NDArrays; deferred init (shape with 0 dims) resolves at first
forward. TPU addition: every Parameter carries an optional `sharding`
(jax.sharding.PartitionSpec) consumed by the parallel trainer to lay the
weight out over the device mesh.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray import NDArray, zeros, array
from .. import initializer as init_mod
from ..initializer import InitDesc


class DeferredInitializationError(MXNetError):
    pass


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default",
                 sharding=None):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self.stype = stype
        self.grad_stype = grad_stype
        self.sharding = sharding  # PartitionSpec | None (TPU-native)
        self.attrs: Dict[str, str] = {}
        self._data: Optional[NDArray] = None
        self._grad: Optional[NDArray] = None
        self._deferred_init = None  # (init, ctx, default_init)
        self._ctx_list: List[Context] = []

    # -- properties ----------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {req}")
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._grad = None
                self._data._ag_node = None
            else:
                self._init_grad()

    def _shape_complete(self):
        return self.shape is not None and all(s > 0 for s in self.shape)

    # -- init ----------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        default_init = default_init or init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if not self._shape_complete():
            if self.allow_deferred_init:
                self._deferred_init = (init, list(ctx), default_init)
                return
            raise MXNetError(
                f"cannot initialize parameter '{self.name}' with incomplete "
                f"shape {self.shape}; set allow_deferred_init or give full shape")
        self._finish_init(init, default_init)

    def _finish_init(self, init, default_init):
        import jax
        ctx = self._ctx_list[0] if self._ctx_list else current_context()
        # deferred init can trigger inside a shape-probe trace: parameter
        # material must always be concrete, so escape any live trace
        with jax.ensure_compile_time_eval():
            data = zeros(self.shape, ctx=ctx, dtype=self.dtype)
            initializer = init_mod.create(init) if init is not None else \
                (init_mod.create(self.init) if self.init is not None else
                 init_mod.create(default_init))
            initializer(InitDesc(self.name, self.attrs), data)
            self._data = data
            self._deferred_init = None
            if self._grad_req != "null":
                self._init_grad()

    def _finish_deferred_init(self, in_shape_hint=None):
        if self._deferred_init is None:
            raise DeferredInitializationError(
                f"parameter '{self.name}' not fully initialized")
        init, ctx, default_init = self._deferred_init
        if not self._shape_complete():
            raise DeferredInitializationError(
                f"deferred parameter '{self.name}' still has unknown shape {self.shape}")
        self._ctx_list = ctx
        self._finish_init(init, default_init)

    def _init_grad(self):
        from .. import autograd
        self._grad = zeros(self.shape, ctx=self._data.ctx, dtype=self._data.dtype)
        if self.grad_stype == "row_sparse":
            # the grad ARRAY ITSELF is row_sparse (dense-backed) so every
            # consumer — optimizer lazy dispatch, user clipping, kvstore
            # push — sees the same mutable object with stype row_sparse
            from ..ndarray.sparse import RowSparseNDArray
            self._grad = RowSparseNDArray(self._grad._data, self._grad.ctx)
        autograd.mark_variables([self._data], [self._grad], grad_reqs=self._grad_req)

    # -- access --------------------------------------------------------------
    def _check_initialized(self):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"parameter '{self.name}' deferred; run a forward pass first")
            raise MXNetError(
                f"parameter '{self.name}' has not been initialized; call "
                f".initialize() first")

    def data(self, ctx=None) -> NDArray:
        self._check_initialized()
        return self._data

    def list_data(self):
        self._check_initialized()
        return [self._data]

    def grad(self, ctx=None) -> NDArray:
        self._check_initialized()
        if self._grad is None:
            raise MXNetError(f"parameter '{self.name}' has grad_req='null'")
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._data is None and self._deferred_init is not None:
            return self._deferred_init[1]
        self._check_initialized()
        return self._ctx_list or [self._data.ctx]

    def set_data(self, data):
        if self.shape is not None and self._shape_complete():
            if tuple(data.shape) != tuple(self.shape):
                raise MXNetError(
                    f"shape mismatch for '{self.name}': {data.shape} vs {self.shape}")
        self.shape = tuple(data.shape)
        if not isinstance(data, NDArray):
            data = array(data, dtype=self.dtype)
        if self._data is None:
            self._data = data
            if self._grad_req != "null":
                self._init_grad()
        else:
            self._data._set_data(data._data.astype(jnp.dtype(self.dtype)))

    def zero_grad(self):
        if self._grad is not None:
            self._grad._set_data(jnp.zeros(self._grad.shape, self._grad.dtype))

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if self._data is not None:
            self._data = self._data.as_in_context(ctx[0])
            if self._grad_req != "null":
                self._init_grad()

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data._set_data(self._data._data.astype(jnp.dtype(dtype)))
            if self._grad is not None:
                self._init_grad()

    def var(self):
        raise MXNetError("symbolic var() is not part of the TPU framework; "
                         "hybridize() traces directly to XLA")

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"


class Constant(Parameter):
    """Constant parameter (reference gluon/parameter.py Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = array(value)
        self.value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.dtype),
                         init=init_mod.Constant(0), differentiable=False)
        self._data = value

    def _finish_init(self, init, default_init):
        self._data = self.value


class ParameterDict:
    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def get(self, name, **kwargs) -> Parameter:
        full = self._prefix + name
        p = None
        if full in self._params:
            p = self._params[full]
            for k, v in kwargs.items():
                if v is not None and getattr(p, k, None) in (None, 0, (), "write") \
                        and k in ("shape", "dtype", "init"):
                    setattr(p, k, tuple(v) if k == "shape" and isinstance(v, (list, tuple)) else v)
        elif self._shared is not None and full in self._shared:
            p = self._shared[full]
            self._params[full] = p
        if p is not None:
            # storage-type kwargs cannot be silently dropped for a shared
            # parameter: dense vs row_sparse changes training numerics
            # (reference ParameterDict.get asserts attribute consistency)
            for k in ("grad_stype", "stype"):
                want = kwargs.get(k)
                if want is not None and getattr(p, k, "default") != want:
                    raise MXNetError(
                        f"parameter '{full}' already exists with "
                        f"{k}={getattr(p, k, 'default')!r}; requested {want!r}")
            return p
        p = Parameter(full, **kwargs)
        self._params[full] = p
        return p

    def get_constant(self, name, value=None):
        full = self._prefix + name
        if full not in self._params:
            self._params[full] = Constant(full, value)
        return self._params[full]

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        for p in self._params.values():
            p.initialize(init=None, ctx=ctx, default_init=init or init_mod.Uniform(),
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        from ..serialization import save_ndarrays
        d = {}
        for name, p in self._params.items():
            key = name[len(strip_prefix):] if name.startswith(strip_prefix) else name
            d["arg:" + key] = p.data()
        save_ndarrays(filename, d)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..serialization import load_ndarrays
        loaded = load_ndarrays(filename)
        loaded = {k.split(":", 1)[1] if ":" in k else k: v for k, v in loaded.items()}
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self._params.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise MXNetError(f"parameter {name} missing from {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError(f"extra parameters in file: {sorted(extra)[:5]}")

    def __repr__(self):
        lines = [f"  {p}" for p in self._params.values()]
        return "ParameterDict(\n" + "\n".join(lines) + "\n)"
