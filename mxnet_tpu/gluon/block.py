"""Block / HybridBlock (reference python/mxnet/gluon/block.py:228,838).

`hybridize()` is the reference's CachedOp boundary (src/imperative/cached_op.h)
re-designed for XLA (SURVEY.md §3.3): the block's forward is traced ONCE per
(input-signature, train-mode) into a single `jax.vjp`-based artifact — the
training forward returns outputs PLUS the VJP residuals, autograd's tape
keeps the residual handle, and `backward()` invokes the compiled pullback
directly, so one training step runs the forward computation exactly once
(the reference's one-CachedOp-artifact contract, not the recompute-forward
mirror mode earlier revisions used). Compiled artifacts live in the
process-wide `mxnet_tpu.engine` cache keyed on (structure fingerprint,
signature, train flag), so N instances of the same model compile once.

Mutable aux state (BatchNorm running stats) is threaded functionally through
`defer_aux_update`: under a trace the new value becomes an extra output and is
written back after the compiled call returns.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

import os

from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray import NDArray
from .. import ndarray as nd
from .. import autograd
from .. import engine as _engine
from .. import random as _rng
from .. import telemetry as _telem
from .parameter import Parameter, ParameterDict, DeferredInitializationError


# ---------------------------------------------------------------------------
# Aux-state side-channel (BatchNorm moving stats etc.)
# ---------------------------------------------------------------------------

_AUX_STACK: List[List[Tuple[Parameter, Any]]] = []
_TRACE_DEPTH = [0]  # >0 while tracing/probing: children fold into the trace
# during a symbolic trace: stack of {id(Parameter): structured name} for the
# root block being exported, so nested blocks name their param Variables by
# the same keys save_parameters uses
_SYM_PARAM_NAMES: list = []


def in_trace() -> bool:
    return _TRACE_DEPTH[0] > 0


def defer_aux_update(param: Parameter, new_raw):
    """Write `new_raw` into param — immediately in eager mode, functionally
    (as an extra traced output) inside a hybridized trace."""
    if _AUX_STACK:
        _AUX_STACK[-1].append((param, jax.lax.stop_gradient(new_raw)))
    elif not in_trace():
        param._data._set_data(new_raw)
    # inside a shape probe (in_trace, no aux stack): drop the abstract update


class _NameManager:
    _lock = threading.Lock()
    _counters: Dict[str, int] = {}

    @classmethod
    def fresh(cls, hint: str) -> str:
        with cls._lock:
            i = cls._counters.get(hint, 0)
            cls._counters[hint] = i + 1
        return f"{hint}{i}_"


class _BlockScope:
    """Hierarchical naming (reference gluon/block.py _BlockScope): a block
    created inside a parent's `with self.name_scope():` gets the parent's
    prefix prepended and draws its counter from the PARENT's per-hint
    counters, so `Net(prefix='mynet_')` yields `mynet_dense0_weight` —
    exactly the reference naming contract save/load and symbol export
    rely on."""

    _tls = threading.local()

    def __init__(self, block: "Block"):
        self._block = block
        self._counters: Dict[str, int] = {}

    @classmethod
    def _stack(cls) -> List["_BlockScope"]:
        st = getattr(cls._tls, "stack", None)
        if st is None:
            st = cls._tls.stack = []
        return st

    @classmethod
    def create_prefix(cls, prefix: Optional[str], hint: str) -> str:
        st = cls._stack()
        if not st:
            return prefix if prefix is not None \
                else _NameManager.fresh(hint)
        scope = st[-1]
        if prefix is None:
            i = scope._counters.get(hint, 0)
            scope._counters[hint] = i + 1
            prefix = f"{hint}{i}_"
        return scope._block.prefix + prefix

    def __enter__(self):
        self._stack().append(self)
        return self

    def __exit__(self, *a):
        self._stack().pop()
        return False


class HookHandle:
    """Detachable hook registration (reference gluon/utils.py HookHandle)."""

    def __init__(self, hooks_list: List, hook):
        self._hooks_list = hooks_list
        self._hook = hook

    def detach(self):
        if self._hook in self._hooks_list:
            self._hooks_list.remove(self._hook)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.detach()
        return False


class Block:
    """Base container (reference gluon/block.py:228)."""

    def __init__(self, prefix: Optional[str] = None, params: Optional[ParameterDict] = None):
        self._empty_init_guard = True
        self._prefix = _BlockScope.create_prefix(
            prefix, type(self).__name__.lower())
        self._params = ParameterDict(self._prefix, shared=params)
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._reg_params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._forward_hooks: List = []
        self._forward_pre_hooks: List = []
        self._scope = _BlockScope(self)

    # -- naming / params -----------------------------------------------------
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._prefix[:-1] if self._prefix.endswith("_") else self._prefix

    @property
    def params(self) -> ParameterDict:
        return self._params

    def name_scope(self):
        return self._scope

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = getattr(self, "_children", None)
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = getattr(self, "_reg_params", None)
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return HookHandle(self._forward_hooks, hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return HookHandle(self._forward_pre_hooks, hook)

    def collect_params(self, select: Optional[str] = None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pattern = re.compile(select)
            ret.update({k: v for k, v in self._params.items() if pattern.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        return ret

    # -- lifecycle -----------------------------------------------------------
    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        from .. import initializer as init_mod
        self.collect_params().initialize(init or init_mod.Uniform(), ctx,
                                         verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._reg_params.values():
            p.cast(dtype)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # -- checkpointing ---------------------------------------------------------
    def _collect_params_with_prefix(self, prefix="") -> "OrderedDict[str, Parameter]":
        """Structural names ('features.0.weight') — stable across instances
        regardless of global name counters (reference block.py same method)."""
        if prefix:
            prefix += "."
        ret = OrderedDict()
        for name, p in self._reg_params.items():
            ret[prefix + name] = p
        for cname, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + cname))
        return ret

    def save_parameters(self, filename, deduplicate=False):
        params = self._collect_params_with_prefix()
        from ..serialization import save_ndarrays
        arg = {"arg:" + k: p.data() for k, p in params.items()}
        save_ndarrays(filename, arg)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, dtype_source="current"):
        from ..serialization import load_ndarrays
        loaded = load_ndarrays(filename)
        loaded = {k.split(":", 1)[1] if ":" in k else k: v for k, v in loaded.items()}
        params = self._collect_params_with_prefix()
        for key, p in params.items():
            if key in loaded:
                p.set_data(loaded[key])
            elif not allow_missing:
                raise MXNetError(f"parameter {p.name} ({key}) missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(f"extra parameters in file: {sorted(extra)[:5]}")

    save_params = save_parameters
    load_params = load_parameters

    # -- execution -------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def summary(self, *inputs):
        lines = [f"{'Layer':<40}{'Output':<24}{'Params':>12}"]
        total = 0
        for name, p in self.collect_params().items():
            n = 1
            for s in (p.shape or ()):
                n *= s
            total += n
            lines.append(f"{name:<40}{str(p.shape):<24}{n:>12}")
        lines.append(f"Total params: {total}")
        print("\n".join(lines))

    def __repr__(self):
        mods = "\n".join(f"  ({k}): {v!r}".replace("\n", "\n  ")
                         for k, v in self._children.items())
        return f"{type(self).__name__}(\n{mods}\n)" if mods else f"{type(self).__name__}()"


# ---------------------------------------------------------------------------
# HybridBlock
# ---------------------------------------------------------------------------

def _flatten_nd(args):
    """Flatten a nested structure of NDArrays -> (raw leaves, treedef)."""
    leaves, treedef = jax.tree_util.tree_flatten(
        args, is_leaf=lambda x: isinstance(x, NDArray))
    raw = [l._data if isinstance(l, NDArray) else l for l in leaves]
    return raw, treedef, [isinstance(l, NDArray) for l in leaves]


class _CachedGraph:
    """One shared compiled artifact per (fingerprint, signature, train) key.

    - ``fwd``:     jitted inference forward ``(key, *flat) -> (outs, aux)``
    - ``fwd_res``: jitted training forward ``(key, *flat) -> (outs, aux,
                   residuals)`` — the forward of ``jax.vjp``, residuals out
    - ``bwd``:     jitted pullback ``(residuals, cots) -> input cotangents``
                   (never re-runs the forward)

    Aux params (BN running stats) are stored as structural PATHS so a
    different instance of the same model can map them onto its own
    Parameters when it reuses the artifact from the engine cache.
    """

    __slots__ = ("fwd", "fwd_res", "bwd", "bwd_recompute", "out_treedef",
                 "res_treedef", "aux_paths", "aux_params_builder",
                 "builder_id", "cost", "bwd_cost")

    def __init__(self):
        self.fwd = None
        self.fwd_res = None
        self.bwd = None
        self.bwd_recompute = None
        self.out_treedef = None
        self.res_treedef = None
        self.aux_paths = None          # set on first trace
        self.aux_params_builder = None
        self.builder_id = None
        self.cost = None               # cost_analysis capture (telemetry on)
        self.bwd_cost = None           # pullback cost: real cost_analysis of
                                       # the compiled vjp where available,
                                       # else the 2x-fwd heuristic (flagged
                                       # "estimated" in the roofline ledger)


class HybridBlock(Block):
    """reference gluon/block.py:838; hybridize() == trace-to-XLA cache."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cached_graphs: Dict[Any, list] = {}
        self._fingerprint_memo: Optional[str] = None
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  inline_limit=2, **kwargs):
        self._active = active
        self._flags = dict(static_alloc=static_alloc, static_shape=static_shape)
        self._cached_graphs.clear()
        self._fingerprint_memo = None
        super().hybridize(active, **kwargs)

    def clear_cache(self):
        # drop this block's entries from the process-wide cache too, so a
        # structurally-stale artifact can't be handed back on the next call
        if self._fingerprint_memo is not None:
            _engine.clear_compilation_cache(self._fingerprint_memo)
        self._fingerprint_memo = None
        self._cached_graphs.clear()
        for c in self._children.values():
            if isinstance(c, HybridBlock):
                c.clear_cache()

    def cast(self, dtype):
        if self._fingerprint_memo is not None:
            _engine.clear_compilation_cache(self._fingerprint_memo)
        self._fingerprint_memo = None
        self._cached_graphs.clear()
        super().cast(dtype)

    # -- deferred shape inference ---------------------------------------------
    def infer_shape(self, *args):
        """Layers override to resolve deferred param shapes from inputs."""

    def _ensure_params_ready(self, args):
        params = self.collect_params()
        pending = [p for p in params.values() if p._deferred_init is not None]
        if not pending:
            return
        # run shape inference down the tree by a dry eager call per block
        self._shape_probe(*args)
        for p in pending:
            if p._deferred_init is not None:
                p._finish_deferred_init()

    def _shape_probe(self, *args):
        """Default probe: call infer_shape hooks recursively by executing the
        forward with ShapeDtypeStruct abstract eval."""
        def run(*raw):
            nds = [NDArray(r) for r in raw]
            with autograd.pause():
                out = self._forward_unhybridized(*nds)
            flat, _, _ = _flatten_nd(out)
            return tuple(flat)
        raw, _, _ = _flatten_nd(list(args))
        _TRACE_DEPTH[0] += 1
        try:
            try:
                jax.eval_shape(run, *raw)
            except DeferredInitializationError:
                raise
            except Exception:
                # some layers need concrete values; fall back to real execution
                nds = [NDArray(r) for r in raw]
                with autograd.pause():
                    self._forward_unhybridized(*nds)
        finally:
            _TRACE_DEPTH[0] -= 1

    # -- forward ---------------------------------------------------------------
    def forward(self, *args):
        x = args[0] if args else None
        if not isinstance(x, NDArray):
            from ..symbol.symbol import Symbol
            if isinstance(x, Symbol):
                # symbolic trace: gluon -> Symbol graph (reference
                # HybridBlock._build_cache's symbol pass; used by export)
                return self._forward_symbolic(*args)
            raise MXNetError(f"{type(self).__name__}.forward expects NDArray input")
        # inside an enclosing trace, fold into the same XLA program instead of
        # nesting another cached graph (keeps one fused computation)
        use_cached = self._active and not in_trace()
        try:
            if use_cached:
                return self._call_cached(*args)
            return self._forward_unhybridized(*args)
        except DeferredInitializationError:
            self._ensure_params_ready(list(args))
            if use_cached:
                return self._call_cached(*args)
            return self._forward_unhybridized(*args)

    def _forward_unhybridized(self, *args):
        kwargs = {}
        for name, p in self._reg_params.items():
            try:
                kwargs[name] = p.data()
            except DeferredInitializationError:
                self.infer_shape(*args)
                if p._deferred_init is not None:
                    p._finish_deferred_init()
                kwargs[name] = p.data()
        return self.hybrid_forward(nd, *args, **kwargs)

    def _forward_symbolic(self, *args):
        """Trace this block into a Symbol graph. Parameter Variables are
        named by their structured path (the save_parameters key), so the
        exported symbol binds directly against the exported params file."""
        from .. import symbol as sym_mod
        own_map = not _SYM_PARAM_NAMES
        if own_map:
            _SYM_PARAM_NAMES.append(
                {id(p): k for k, p in
                 self._collect_params_with_prefix().items()})
        name_of = _SYM_PARAM_NAMES[-1]
        try:
            kwargs = {}
            for name, p in self._reg_params.items():
                kwargs[name] = sym_mod.Variable(name_of.get(id(p), p.name))
            return self.hybrid_forward(sym_mod, *args, **kwargs)
        finally:
            if own_map:
                _SYM_PARAM_NAMES.pop()

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- CachedOp path ---------------------------------------------------------
    def _signature(self, raw_inputs):
        return (tuple((tuple(r.shape), str(r.dtype)) for r in raw_inputs),
                autograd.is_training())

    def _fingerprint(self) -> str:
        if self._fingerprint_memo is None:
            self._fingerprint_memo = _engine.structural_fingerprint(self)
        return self._fingerprint_memo

    def _resolve_aux_params(self, graph: _CachedGraph) -> Optional[List[Parameter]]:
        """Map the artifact's aux-param paths onto THIS instance's Parameters.
        Returns None when the artifact can't be adopted (an aux param of the
        builder has no structural path and we are not the builder)."""
        if not graph.aux_paths:
            return []
        if None not in graph.aux_paths:
            by_path = self._collect_params_with_prefix()
            try:
                return [by_path[p] for p in graph.aux_paths]
            except KeyError:
                return None
        return graph.aux_params_builder if graph.builder_id == id(self) \
            else None

    def _call_cached(self, *args):
        params_dict = self.collect_params()
        plist = [p for p in params_dict.values() if p._data is not None or p._deferred_init is not None]
        for p in plist:
            if p._deferred_init is not None:
                raise DeferredInitializationError(p.name)
        raw_inputs, in_treedef, _ = _flatten_nd(list(args))
        raw_params = [p._data._data for p in plist]
        sig = self._signature(raw_inputs)
        entry = self._cached_graphs.get(sig)
        if entry is None:
            cache_key = ("gluon", self._fingerprint(), sig)
            graph = _engine.lookup(cache_key)
            if graph is None:
                with _engine.compile_timer(f"gluon:{type(self).__name__}"):
                    graph = self._build_graph(args, in_treedef, plist, sig)
                _engine.insert(cache_key, graph)
            entry = [graph, None]  # aux mapping resolved after first trace
            self._cached_graphs[sig] = entry
        graph = entry[0]
        key = _rng.next_key_raw()
        recording = autograd.is_recording()
        # MXNET_TPU_REMAT_BWD=1: rematerialized backward (the reference's
        # MXNET_BACKWARD_DO_MIRROR) — forward saves NO residuals and the
        # pullback re-runs the forward, trading ~2x forward FLOPs for
        # activation memory. Default is the residual-caching vjp artifact.
        remat = os.environ.get("MXNET_TPU_REMAT_BWD", "") not in ("", "0")
        all_raw = tuple(raw_inputs) + tuple(raw_params)
        if _telem._ENABLED and graph.cost is None:
            # artifact-build-time FLOPs capture for the MFU/roofline gauges
            # (one AOT lower+compile per artifact, shared with jax's caches)
            graph.cost = _engine.estimate_cost(graph.fwd, key, *all_raw,
                                               kind="gluon_fwd")
        res = None
        if recording and not remat:
            outs_flat, aux_vals, res = graph.fwd_res(key, *all_raw)
        else:
            outs_flat, aux_vals = graph.fwd(key, *all_raw)
        fwd_flops = (graph.cost or {}).get("flops", 0.0)
        # roofline region: one row per shared artifact (structural
        # fingerprint), so N instances of one block aggregate together
        region = (f"gluon:{type(self).__name__}#{self._fingerprint()[:6]}"
                  if _telem._ENABLED else None)
        _engine.record_execution(
            "fwd", fwd_flops,
            bytes_accessed=(graph.cost or {}).get("bytes_accessed", 0.0),
            region=region, cost=graph.cost)
        if entry[1] is None:
            aux_params = self._resolve_aux_params(graph)
            if aux_params is None:
                # artifact not adoptable by this instance: build a private
                # one (keyed by instance identity) and redo the call
                cache_key = ("gluon", self._fingerprint(), sig, id(self))
                graph = _engine.lookup(cache_key)
                if graph is None:
                    with _engine.compile_timer(
                            f"gluon:{type(self).__name__}"):
                        graph = self._build_graph(args, in_treedef, plist,
                                                  sig)
                    _engine.insert(cache_key, graph)
                entry[0] = graph
                if _telem._ENABLED and graph.cost is None:
                    graph.cost = _engine.estimate_cost(
                        graph.fwd, key, *all_raw, kind="gluon_fwd")
                    fwd_flops = (graph.cost or {}).get("flops", 0.0)
                if recording and not remat:
                    outs_flat, aux_vals, res = graph.fwd_res(key, *all_raw)
                else:
                    outs_flat, aux_vals = graph.fwd(key, *all_raw)
                aux_params = graph.aux_params_builder
            entry[1] = aux_params
        # apply aux updates (BN running stats) outside the trace
        for p, v in zip(entry[1], aux_vals):
            p._data._set_data(v)
        ctx = args[0].ctx if isinstance(args[0], NDArray) else current_context()
        out_nds = [NDArray(o, ctx) for o in outs_flat]
        if recording:
            input_nds = [a for a in jax.tree_util.tree_leaves(
                list(args), is_leaf=lambda x: isinstance(x, NDArray))]
            param_nds = [p._data for p in plist]
            out_dtypes = [o.dtype for o in outs_flat]

            def _bwd_cost_of(_graph, capture, _ffl=fwd_flops):
                """Pullback cost: real cost_analysis of the compiled vjp
                artifact, captured once at first backward (the AOT lower
                shares XLA's caches); falls back to the 2x-forward
                roofline convention, flagged 'estimated' so ledger rows
                built on it render distinguishably."""
                if _graph.bwd_cost is None and _telem._ENABLED:
                    c = capture()
                    if not c.get("flops"):
                        c = {"flops": 2.0 * _ffl, "estimated": 1.0}
                    _graph.bwd_cost = c
                return _graph.bwd_cost or {"flops": 2.0 * _ffl,
                                           "estimated": 1.0}

            def _record_bwd(c, _region=region):
                _engine.record_execution(
                    "bwd", c.get("flops", 0.0),
                    bytes_accessed=c.get("bytes_accessed", 0.0),
                    region=f"{_region}/bwd" if _region else None,
                    estimated=bool(c.get("estimated")), cost=c)

            if res is not None:
                def vjp_fn(cots, _graph=graph, _res=res, _dts=out_dtypes):
                    cots_t = cots if isinstance(cots, tuple) else (cots,)
                    # the compiled pullback's cotangent avals are fixed;
                    # cast mismatched head grads instead of tripping a
                    # vjp error
                    cots_t = tuple(
                        c if getattr(c, "dtype", None) == dt else
                        jnp.asarray(c, dt)
                        for c, dt in zip(cots_t, _dts))
                    _record_bwd(_bwd_cost_of(
                        _graph, lambda: _engine.estimate_cost(
                            _graph.bwd, _res, cots_t, kind="gluon_bwd")))
                    return _graph.bwd(_res, cots_t)
            else:
                def vjp_fn(cots, _graph=graph, _key=key, _all_raw=all_raw,
                           _dts=out_dtypes):
                    cots_t = cots if isinstance(cots, tuple) else (cots,)
                    cots_t = tuple(
                        c if getattr(c, "dtype", None) == dt else
                        jnp.asarray(c, dt)
                        for c, dt in zip(cots_t, _dts))
                    _record_bwd(_bwd_cost_of(
                        _graph, lambda: _engine.estimate_cost(
                            _graph.bwd_recompute, _key, _all_raw, cots_t,
                            kind="gluon_bwd_recompute")))
                    return _graph.bwd_recompute(_key, _all_raw, cots_t)

            autograd.record_op(vjp_fn, input_nds + param_nds, out_nds,
                               out_is_tuple=len(out_nds) > 1, residuals=res)
        out_tree = jax.tree_util.tree_unflatten(graph.out_treedef, out_nds)
        return out_tree

    def _build_graph(self, args, in_treedef, plist, sig) -> _CachedGraph:
        graph = _CachedGraph()
        graph.builder_id = id(self)
        n_in = len(_flatten_nd(list(args))[0])
        train_flag = sig[1]
        block = self
        first_trace = {"done": False}

        def pure_fn(key_raw, *flat):
            _engine.record_trace()
            raw_inputs = flat[:n_in]
            raw_params = flat[n_in:]
            in_nds = [NDArray(r) for r in raw_inputs]
            args_nd = jax.tree_util.tree_unflatten(in_treedef, in_nds)
            saved = [p._data._data for p in plist]
            aux_collector: List[Tuple[Parameter, Any]] = []
            _AUX_STACK.append(aux_collector)
            _TRACE_DEPTH[0] += 1
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(train_flag)
            _rng.push_trace_key(key_raw)
            try:
                for p, r in zip(plist, raw_params):
                    p._data._data = r
                out = block._forward_unhybridized(*args_nd)
            finally:
                _rng.pop_trace_key()
                for p, s in zip(plist, saved):
                    p._data._data = s
                _AUX_STACK.pop()
                _TRACE_DEPTH[0] -= 1
                autograd.set_recording(prev_rec)
                autograd.set_training(prev_train)
            out_flat, out_treedef, _ = _flatten_nd(out)
            if not first_trace["done"]:
                graph.out_treedef = out_treedef
                aux_order = [p for p, _ in aux_collector]
                path_of = {id(p): k for k, p in
                           block._collect_params_with_prefix().items()}
                graph.aux_paths = [path_of.get(id(p)) for p in aux_order]
                graph.aux_params_builder = aux_order
                first_trace["done"] = True
            return tuple(out_flat), tuple(v for _, v in aux_collector)

        graph.fwd = jax.jit(pure_fn)

        def fwd_res_impl(key_raw, *flat):
            # ONE vjp artifact: forward emits outputs + aux + residuals; the
            # pullback below consumes the residuals without recomputing the
            # forward (jax's vjp closure is a Partial pytree, so its leaves
            # cross the jit boundary as ordinary arrays)
            def f(*ins):
                return pure_fn(key_raw, *ins)

            outs, vjp_fn, aux = jax.vjp(f, *flat, has_aux=True)
            res_leaves, res_treedef = jax.tree_util.tree_flatten(vjp_fn)
            graph.res_treedef = res_treedef
            return outs, aux, tuple(res_leaves)

        graph.fwd_res = jax.jit(fwd_res_impl)

        def bwd_impl(res_leaves, cots):
            vjp_fn = jax.tree_util.tree_unflatten(graph.res_treedef,
                                                  list(res_leaves))
            return vjp_fn(tuple(cots))

        graph.bwd = jax.jit(bwd_impl)

        def bwd_recompute_impl(key_raw, all_raw, cots):
            # MXNET_TPU_REMAT_BWD mode: re-derive the forward inside the
            # pullback (never compiled unless that mode is active)
            def fwd_only(*flat):
                outs, _aux = pure_fn(key_raw, *flat)
                return outs

            _, vjp = jax.vjp(fwd_only, *all_raw)
            return vjp(tuple(cots))

        graph.bwd_recompute = jax.jit(bwd_recompute_impl)
        return graph

    # -- deployment -----------------------------------------------------------
    def export(self, path, epoch=0, remove_amp_cast=True, n_inputs=1):
        """Serialize to symbol-JSON + params (reference HybridBlock.export,
        python/mxnet/gluon/block.py:1150): the block is traced symbolically
        into a Symbol graph whose parameter Variables carry the structured
        save_parameters names, and the params file uses the reference
        arg:/aux: checkpoint format — so `SymbolBlock.imports`,
        `model.load_checkpoint`, Module, and the ONNX exporter can all
        consume the artifact without the python model code."""
        from .. import symbol as sym_mod
        from ..model import save_params_file

        inputs = [sym_mod.Variable("data" if i == 0 else f"data{i}")
                  for i in range(n_inputs)]
        out = self(*inputs)
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        out.save(f"{path}-symbol.json")
        arg, aux = {}, {}
        aux_names = set(out.list_auxiliary_states())
        for k, p in self._collect_params_with_prefix().items():
            (aux if k in aux_names else arg)[k] = p.data()
        save_params_file(f"{path}-{epoch:04d}.params", arg, aux)
        return f"{path}-symbol.json", f"{path}-{epoch:04d}.params"

    def optimize_for(self, x, backend=None, **kwargs):
        self.hybridize()
        return self(x)


class SymbolBlock(HybridBlock):
    """Serve an exported symbol graph without its python model code
    (reference gluon/block.py:1193; together with HybridBlock.export this
    replaces the c_predict_api load-and-run deployment path)."""

    def __init__(self, outputs, inputs, params=None, prefix=None, **kwargs):
        super().__init__(prefix=prefix or "", **kwargs)
        from .. import symbol as sym_mod
        if isinstance(outputs, (list, tuple)):
            outputs = sym_mod.Group(list(outputs))
        self._out_sym = outputs
        self._input_names = [i.name if hasattr(i, "name") else str(i)
                             for i in (inputs if isinstance(inputs, (list, tuple))
                                       else [inputs])]
        self._arg_params = dict(params or {})
        self._exec_cache = {}
        self._param_objs = None
        self._feed_cache = {}

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as sym_mod
        from ..model import load_params
        out = sym_mod.load(symbol_file)
        params = {}
        if param_file:
            arg, aux = load_params(param_file)
            params = {**arg, **aux}
        if isinstance(input_names, str):
            input_names = [input_names]
        blk = SymbolBlock(out, [sym_mod.Variable(n) for n in input_names],
                          params=params)
        blk._ctx = ctx
        return blk

    def _live_params(self):
        # persistent Parameter objects so collect_params()/set_data/load
        # feed every subsequent forward (not a first-call snapshot)
        if self._param_objs is None:
            from .parameter import Parameter, ParameterDict
            pd = ParameterDict()
            for k, v in self._arg_params.items():
                p = Parameter(k, shape=tuple(v.shape), dtype=str(v.dtype),
                              grad_req="null")
                p.set_data(v if isinstance(v, NDArray) else NDArray(v._data))
                pd._params[k] = p
            self._param_objs = pd
        return self._param_objs

    def forward(self, *args):
        from ..context import current_context
        ctx = getattr(self, "_ctx", None) or \
            (args[0].ctx if isinstance(args[0], NDArray) else current_context())
        # ctx is part of the key: each device gets its own bound executor,
        # so a ctx-B call never reuses the ctx-A binding with ctx-B feeds
        key = (str(ctx),) + tuple((tuple(a.shape), str(a.dtype)) for a in args)
        feed = dict(zip(self._input_names, args))
        # params follow the bind ctx; the device copy is cached per ctx and
        # per (array identity, version) so serving pays it once per device,
        # not per call — even when calls alternate between devices
        conv = self._feed_cache.setdefault(ctx, {})
        for k, p in self._live_params()._params.items():
            d = p.data()
            ent = conv.get(k)
            if ent is None or ent[0] is not d or ent[1] != d.version:
                conv[k] = ent = (d, d.version, d.as_in_context(ctx))
            feed[k] = ent[2]
        ex = self._exec_cache.get(key)
        if ex is None:
            ex = self._out_sym.bind(ctx, dict(feed))
            self._exec_cache[key] = ex
        # always re-feed current param values so post-construction
        # set_data/load on collect_params() results affect inference
        outs = ex.forward(**feed)
        return outs[0] if len(outs) == 1 else outs

    def collect_params(self, select=None):
        import re as _re
        from .parameter import ParameterDict
        live = self._live_params()
        if not select:
            return live
        pat = _re.compile(select)
        pd = ParameterDict()
        for k, p in live._params.items():
            if pat.match(k):
                pd._params[k] = p
        return pd
