"""Learning-rate schedulers (reference python/mxnet/lr_scheduler.py)."""
from __future__ import annotations

import math


class LRScheduler:
    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0,
                 warmup_mode="linear"):
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_final_lr = base_lr
        self.warmup_mode = warmup_mode

    def get_warmup_lr(self, num_update):
        if self.warmup_mode == "linear":
            inc = (self.warmup_final_lr - self.warmup_begin_lr) * num_update / self.warmup_steps
            return self.warmup_begin_lr + inc
        if self.warmup_mode == "constant":
            return self.warmup_begin_lr
        raise ValueError(self.warmup_mode)

    def __call__(self, num_update):
        raise NotImplementedError

    # Factor/MultiFactor schedulers MUTATE on __call__ (decayed base_lr,
    # count / cur_step_ind) — a resumed run that drops these re-decays
    # from scratch and sees a different lr at step K+1. Elastic snapshots
    # persist them (mxnet_tpu/elastic/state.py sched_state).
    _STATE_ATTRS = ("base_lr", "count", "cur_step_ind")

    def state_dict(self):
        return {k: getattr(self, k) for k in self._STATE_ATTRS
                if hasattr(self, k)}

    def load_state_dict(self, d):
        for k in self._STATE_ATTRS:
            if k in d and hasattr(self, k):
                setattr(self, k, d[k])


class FactorScheduler(LRScheduler):
    def __init__(self, step, factor=1.0, stop_factor_lr=1e-8, base_lr=0.01,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr = max(self.base_lr * self.factor, self.stop_factor_lr)
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    def __init__(self, step, factor=1.0, base_lr=0.01, warmup_steps=0,
                 warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        self.step = list(step)
        self.factor = factor
        self.cur_step_ind = 0

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        while self.cur_step_ind < len(self.step) and num_update >= self.step[self.cur_step_ind]:
            self.base_lr *= self.factor
            self.cur_step_ind += 1
        return self.base_lr


class PolyScheduler(LRScheduler):
    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        self.power = pwr
        self.base_lr_orig = base_lr
        self.max_update = max_update
        self.final_lr = final_lr
        self.max_steps = max_update - warmup_steps

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        if num_update <= self.max_update:
            frac = 1 - (num_update - self.warmup_steps) / self.max_steps
            return self.final_lr + (self.base_lr_orig - self.final_lr) * frac ** self.power
        return self.final_lr


class CosineScheduler(LRScheduler):
    def __init__(self, max_update, base_lr=0.01, final_lr=0,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        self.base_lr_orig = base_lr
        self.max_update = max_update
        self.final_lr = final_lr
        self.max_steps = max_update - warmup_steps

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        if num_update <= self.max_update:
            t = (num_update - self.warmup_steps) / self.max_steps
            return self.final_lr + (self.base_lr_orig - self.final_lr) * \
                (1 + math.cos(math.pi * t)) / 2
        return self.final_lr
