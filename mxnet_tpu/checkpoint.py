"""Elastic sharded checkpoint / resume.

Capability UPLIFT over the reference (SURVEY.md §5-c): the reference's
recovery story is "checkpoint + relaunch" with no in-framework resume —
ps-lite only exposes dead-node counts. Here:

  - CheckpointManager saves the FULL training state (sharded parameters,
    optimizer state, step counter, RNG) via orbax — per-shard parallel IO,
    resharding on restore (save on N chips, resume on M), atomic step
    directories, retention policy;
  - resume_or_init() implements the elastic pattern: on boot every worker
    restores the latest complete step if one exists, else starts fresh —
    a preempted/rescheduled job self-heals without operator action;
  - DataParallelTrainer gains save/restore hooks carrying its donated
    device buffers directly (no host round-trip through gluon Parameters).
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as _np
import jax

from .base import MXNetError

try:
    import orbax.checkpoint as _ocp
    _HAS_ORBAX = True
except ImportError:  # pragma: no cover
    _HAS_ORBAX = False


class CheckpointManager:
    """Step-indexed sharded checkpoints with retention + atomicity."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        if not _HAS_ORBAX:
            raise MXNetError("orbax is unavailable; use mx.nd.save / "
                             "save_checkpoint for single-host checkpoints")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        opts = _ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            create=True)
        self._mgr = _ocp.CheckpointManager(self.directory, options=opts)

    def save(self, step: int, state: Dict[str, Any], force: bool = False,
             wait: bool = False):
        """state: pytree of jax arrays / numpy / scalars."""
        saved = self._mgr.save(step, args=_ocp.args.StandardSave(state),
                               force=force)
        if wait:
            self._mgr.wait_until_finished()
        return saved

    def restore(self, step: Optional[int] = None,
                like: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Restore `step` (default latest). Pass `like` (a pytree of arrays
        with target shardings) to reshard on restore — save on N devices,
        resume on M."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise MXNetError(f"no checkpoint found in {self.directory}")
        if like is not None:
            tgt = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=getattr(x, "sharding", None))
                if hasattr(x, "shape") else x, like)
            return self._mgr.restore(step,
                                     args=_ocp.args.StandardRestore(tgt))
        # no target: rebuild one from saved metadata WITHOUT shardings —
        # orbax would otherwise try to resolve the devices the checkpoint
        # was written on, which may no longer exist (the elastic case)
        meta = self._mgr.item_metadata(step)
        tree = getattr(meta, "tree", None) or getattr(meta, "item_metadata",
                                                      None) or meta

        dev = jax.config.jax_default_device or jax.devices()[0]
        sh = jax.sharding.SingleDeviceSharding(dev)

        def _as_sds(m):
            shape = getattr(m, "shape", None)
            dtype = getattr(m, "dtype", None)
            if shape is not None and dtype is not None:
                return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sh)
            return m
        tgt = jax.tree_util.tree_map(_as_sds, tree)
        return self._mgr.restore(step, args=_ocp.args.StandardRestore(tgt))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def resume_or_init(directory: str, init_fn, max_to_keep: int = 3):
    """The elastic-boot pattern: restore the newest complete checkpoint if
    one exists, else call init_fn() for a fresh state.

    Returns (manager, state, start_step).
    """
    mgr = CheckpointManager(directory, max_to_keep=max_to_keep)
    step = mgr.latest_step()
    if step is not None:
        like = init_fn()
        state = mgr.restore(step, like=like)
        return mgr, state, int(step) + 1
    return mgr, init_fn(), 0


# ---------------------------------------------------------------------------
# DataParallelTrainer integration
# ---------------------------------------------------------------------------

def trainer_state(trainer) -> Dict[str, Any]:
    """Snapshot a DataParallelTrainer's full training state (device buffers
    go straight to orbax — no host copy). Keys are POSITIONAL ("p3"):
    gluon parameter names embed process-global counters (dense0 vs dense1
    for the same layer rebuilt after restart) and would never match."""
    from . import random as _rng
    state = {
        "params": {f"p{i}": w for i, w in enumerate(trainer._params_raw)},
        "opt_state": {f"p{i}": s for i, s in enumerate(trainer._opt_state)},
        "step": _np.int64(trainer._t),
        "rng": _np.asarray(_rng.get_state_raw()),
    }
    if trainer._scaler is not None:  # fp16 dynamic loss scaling
        state["loss_scale"] = _np.float64(trainer._scaler.loss_scale)
        state["scaler_unskipped"] = _np.int64(trainer._scaler._unskipped)
    return state


def load_trainer_state(trainer, state: Dict[str, Any]):
    """Install a restored snapshot into a freshly-constructed trainer."""
    params = state["params"]
    opt = state["opt_state"]
    n = len(trainer._plist)
    if len(params) != n:
        raise MXNetError(
            f"checkpoint has {len(params)} parameters, trainer has {n} — "
            "architecture mismatch")
    trainer._params_raw = [params[f"p{i}"] for i in range(n)]
    trainer._opt_state = [
        tuple(v) if isinstance(v := opt[f"p{i}"], (list, tuple)) else v
        for i in range(n)]
    trainer._t = int(state["step"])
    trainer.optimizer.num_update = trainer._t
    if "rng" in state:
        from . import random as _rng
        _rng.set_state_raw(state["rng"])
    if trainer._scaler is not None and "loss_scale" in state:
        trainer._scaler.loss_scale = float(state["loss_scale"])
        trainer._scaler._unskipped = int(state.get("scaler_unskipped", 0))
    trainer.sync()
    return trainer


def save_trainer(mgr: CheckpointManager, trainer, force: bool = False,
                 wait: bool = True):
    return mgr.save(trainer._t, trainer_state(trainer), force=force, wait=wait)


def restore_trainer(mgr: CheckpointManager, trainer,
                    step: Optional[int] = None):
    state = mgr.restore(step, like=trainer_state(trainer))
    return load_trainer_state(trainer, state)
