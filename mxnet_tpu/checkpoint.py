"""Orbax-backed checkpoint shim (legacy path).

The first-class fault-tolerance subsystem is ``mxnet_tpu.elastic``
(docs/checkpointing.md): async sharded snapshots with no gather and no
host sync on the step path, trainer-aware resharding restore, resumable
input feeds, and preemption handling. This module remains as the
orbax-format compatibility surface — generic pytree checkpoints, plus
the original trainer save/restore hooks — for checkpoints that must
interoperate with other orbax consumers.

No-target restore is manifest-driven: ``save`` writes a
``mx-leaves-<step>.json`` sidecar describing the tree (container
structure + per-leaf shape/dtype), and ``restore`` rebuilds the orbax
target from it — no devices from the saving run needed, the elastic
case. Checkpoints written before the sidecar existed fall back to
sniffing orbax's per-version metadata object (the old
``getattr(meta, "tree", ...)`` chain) with a DeprecationWarning.
"""
from __future__ import annotations

import json
import os
import warnings
from typing import Any, Dict, Optional

import numpy as _np
import jax

from .base import MXNetError

try:
    import orbax.checkpoint as _ocp
    _HAS_ORBAX = True
except ImportError:  # pragma: no cover
    _HAS_ORBAX = False


def _leaf_spec_of(tree):
    """JSON-able mirror of a state tree: containers kept, array leaves
    reduced to shape+dtype (the sidecar ``restore`` rebuilds from)."""
    if isinstance(tree, dict):
        return {str(k): _leaf_spec_of(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [_leaf_spec_of(v) for v in tree]
    shape = getattr(tree, "shape", None)
    dtype = getattr(tree, "dtype", None)
    if shape is not None and dtype is not None:
        return {"__leaf__": True, "shape": [int(d) for d in shape],
                "dtype": str(_np.dtype(dtype))}
    return {"__opaque__": True}


def _target_from_spec(spec, sharding):
    if isinstance(spec, list):
        return [_target_from_spec(v, sharding) for v in spec]
    if spec.get("__leaf__"):
        return jax.ShapeDtypeStruct(tuple(spec["shape"]),
                                    _np.dtype(spec["dtype"]),
                                    sharding=sharding)
    if spec.get("__opaque__"):
        return None
    return {k: _target_from_spec(v, sharding) for k, v in spec.items()}


class CheckpointManager:
    """Step-indexed sharded checkpoints with retention + atomicity."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        if not _HAS_ORBAX:
            raise MXNetError("orbax is unavailable; use mx.nd.save / "
                             "save_checkpoint for single-host checkpoints")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        opts = _ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            create=True)
        self._mgr = _ocp.CheckpointManager(self.directory, options=opts)

    def _sidecar(self, step: int) -> str:
        return os.path.join(self.directory, f"mx-leaves-{int(step)}.json")

    def save(self, step: int, state: Dict[str, Any], force: bool = False,
             wait: bool = False):
        """state: pytree of jax arrays / numpy / scalars."""
        # numpy scalar leaves (np.int64(step) etc.) are not in orbax's
        # STANDARD_ARRAY_TYPES — normalize them to 0-d ndarrays
        state = jax.tree_util.tree_map(
            lambda x: _np.asarray(x) if isinstance(x, _np.generic) else x,
            state)
        saved = self._mgr.save(step, args=_ocp.args.StandardSave(state),
                               force=force)
        if saved:
            # leaf-spec sidecar: what no-target restore rebuilds its orbax
            # target from (atomic, like the checkpoint dirs themselves)
            tmp = self._sidecar(step) + ".tmp"
            with open(tmp, "w") as f:
                json.dump(_leaf_spec_of(state), f)
            os.replace(tmp, self._sidecar(step))
        if wait:
            self._mgr.wait_until_finished()
        return saved

    def restore(self, step: Optional[int] = None,
                like: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Restore `step` (default latest). Pass `like` (a pytree of arrays
        with target shardings) to reshard on restore — save on N devices,
        resume on M."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise MXNetError(f"no checkpoint found in {self.directory}")
        if like is not None:
            tgt = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=getattr(x, "sharding", None))
                if hasattr(x, "shape") else x, like)
            return self._mgr.restore(step,
                                     args=_ocp.args.StandardRestore(tgt))
        # no target: rebuild one WITHOUT the saving run's shardings —
        # orbax would otherwise try to resolve devices that may no longer
        # exist (the elastic case). The leaf-spec sidecar written at save
        # time is authoritative; pre-sidecar checkpoints fall back to
        # sniffing orbax's (version-dependent) metadata object.
        dev = jax.config.jax_default_device or jax.devices()[0]
        sh = jax.sharding.SingleDeviceSharding(dev)
        side = self._sidecar(step)
        if os.path.exists(side):
            with open(side) as f:
                tgt = _target_from_spec(json.load(f), sh)
            return self._mgr.restore(step,
                                     args=_ocp.args.StandardRestore(tgt))
        warnings.warn(
            "restoring a checkpoint without its mx-leaves sidecar: falling "
            "back to orbax metadata sniffing, which depends on the orbax "
            "version the checkpoint was written with. Re-save with this "
            "build (or use mxnet_tpu.elastic snapshots) to get the "
            "manifest-driven restore path.", DeprecationWarning,
            stacklevel=2)
        meta = self._mgr.item_metadata(step)
        tree = getattr(meta, "tree", None) or getattr(meta, "item_metadata",
                                                      None) or meta

        def _as_sds(m):
            shape = getattr(m, "shape", None)
            dtype = getattr(m, "dtype", None)
            if shape is not None and dtype is not None:
                return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sh)
            return m
        tgt = jax.tree_util.tree_map(_as_sds, tree)
        return self._mgr.restore(step, args=_ocp.args.StandardRestore(tgt))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def resume_or_init(directory: str, init_fn, max_to_keep: int = 3):
    """The elastic-boot pattern: restore the newest complete checkpoint if
    one exists, else call init_fn() for a fresh state.

    Returns (manager, state, start_step).
    """
    mgr = CheckpointManager(directory, max_to_keep=max_to_keep)
    step = mgr.latest_step()
    if step is not None:
        like = init_fn()
        state = mgr.restore(step, like=like)
        return mgr, state, int(step) + 1
    return mgr, init_fn(), 0


# ---------------------------------------------------------------------------
# DataParallelTrainer integration
# ---------------------------------------------------------------------------

def trainer_state(trainer) -> Dict[str, Any]:
    """Snapshot a DataParallelTrainer's full training state (device buffers
    go straight to orbax — no host copy). Keys are POSITIONAL ("p3"):
    gluon parameter names embed process-global counters (dense0 vs dense1
    for the same layer rebuilt after restart) and would never match.

    Legacy orbax-format hook; ``trainer.state_dict()`` +
    ``mxnet_tpu.elastic`` is the first-class path (sharded no-gather
    writes, ZeRO support, resharding restore). ``sched`` carries the
    schedule counters a resumed run needs for lr parity at step K+1
    (optimizer num_update / per-index counts / mutable lr-scheduler
    fields) — dropping them was the historical resume bug."""
    from . import random as _rng
    from .elastic import state as _estate
    state = {
        "params": {f"p{i}": w for i, w in enumerate(trainer._params_raw)},
        "opt_state": {f"p{i}": s for i, s in enumerate(trainer._opt_state)},
        "step": _np.int64(trainer._t),
        "rng": _np.asarray(_rng.get_state_raw()),
        "sched": _estate.sched_state(trainer.optimizer),
    }
    if trainer._scaler is not None:  # fp16 dynamic loss scaling
        state["loss_scale"] = _np.float64(trainer._scaler.loss_scale)
        state["scaler_unskipped"] = _np.int64(trainer._scaler._unskipped)
    return state


def load_trainer_state(trainer, state: Dict[str, Any]):
    """Install a restored snapshot into a freshly-constructed trainer."""
    params = state["params"]
    opt = state["opt_state"]
    n = len(trainer._plist)
    if len(params) != n:
        raise MXNetError(
            f"checkpoint has {len(params)} parameters, trainer has {n} — "
            "architecture mismatch")
    trainer._params_raw = [params[f"p{i}"] for i in range(n)]
    trainer._opt_state = [
        tuple(v) if isinstance(v := opt[f"p{i}"], (list, tuple)) else v
        for i in range(n)]
    trainer._t = int(state["step"])
    if state.get("sched"):
        from .elastic import state as _estate
        _estate.install_sched(trainer.optimizer, state["sched"])
    else:  # pre-sched checkpoints: at least realign the update counter
        trainer.optimizer.num_update = trainer._t
    if "rng" in state:
        from . import random as _rng
        _rng.set_state_raw(state["rng"])
    if trainer._scaler is not None and "loss_scale" in state:
        trainer._scaler.loss_scale = float(state["loss_scale"])
        trainer._scaler._unskipped = int(state.get("scaler_unskipped", 0))
    trainer.sync()
    return trainer


def save_trainer(mgr: CheckpointManager, trainer, force: bool = False,
                 wait: bool = True):
    return mgr.save(trainer._t, trainer_state(trainer), force=force, wait=wait)


def restore_trainer(mgr: CheckpointManager, trainer,
                    step: Optional[int] = None):
    state = mgr.restore(step, like=trainer_state(trainer))
    return load_trainer_state(trainer, state)
