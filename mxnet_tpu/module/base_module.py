"""BaseModule (reference python/mxnet/module/base_module.py).

The abstract training-loop contract: fit (base_module.py:409), score (:176),
predict (:320), plus the forward/backward/update primitives subclasses
implement. The epoch loop is kept structurally identical to the reference so
callbacks (Speedometer, do_checkpoint) and metrics drop in unchanged.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, List, Optional

from ..base import MXNetError
from .. import metric as metric_mod
from .. import io as io_mod
from .. import telemetry as _telem


class BatchEndParam:
    """Callback payload (reference base_module.py uses a namedtuple)."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def _as_metric(m):
    if m is None:
        return metric_mod.create("acc")
    if isinstance(m, metric_mod.EvalMetric):
        return m
    return metric_mod.create(m)


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- abstract surface ----------------------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    @property
    def symbol(self):
        return self._symbol

    # -- composed drivers (reference base_module.py) -------------------------
    def forward_backward(self, data_batch):
        """(base_module.py:193)"""
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """(base_module.py:176)"""
        assert self.binded and self.params_initialized
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        nbatch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                _invoke_callbacks(batch_end_callback,
                                  BatchEndParam(epoch, nbatch, eval_metric))
        if score_end_callback is not None:
            _invoke_callbacks(score_end_callback,
                              BatchEndParam(epoch, nbatch, eval_metric))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False, sparse_row_id_fn=None):
        """(base_module.py:320)"""
        from ..ndarray import concat
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            outs = self.get_outputs()
            pad = getattr(eval_batch, "pad", 0) or 0
            if pad:
                outs = [o[:o.shape[0] - pad] for o in outs]
            output_list.append(outs)
        if not output_list:
            return []
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [concat(*[b[i] for b in output_list], dim=0)
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The classic epoch loop (reference base_module.py:409)."""
        assert num_epoch is not None, "please specify number of epochs"
        from ..initializer import Uniform
        initializer = initializer or Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True,
                  force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        eval_metric = _as_metric(eval_metric)
        validation_metric = validation_metric or eval_metric

        # Async input pipeline + bounded in-flight dispatch
        # (engine/async_feed, docs/input_pipeline.md): batches arrive
        # already device_put by a background producer, the loop dispatches
        # up to MXNET_TPU_INFLIGHT_STEPS steps ahead, and per-step metric
        # accumulation stays on device — the epoch boundary below is the
        # drain point. MXNET_TPU_FEED_DEPTH=0 restores the sync loop.
        from ..engine import async_feed as _feed
        train_data = _feed.maybe_wrap(train_data, name="module")
        if eval_data is not None:
            eval_data = _feed.maybe_wrap(eval_data, name="module-eval")
        window = _feed.DispatchWindow(name="module")

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            if _telem._ENABLED:
                _telem.set_epoch(epoch)
            for data_batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                try:
                    # bound the dispatch pipeline on this step's outputs;
                    # on per-device dispatch order their readiness implies
                    # the whole step (fwd+bwd+update) retired
                    outs = self.get_outputs()
                    window.admit([getattr(o, "_data", o) for o in outs])
                except Exception:
                    pass  # modules without materialized outputs stay sync
                if _telem._ENABLED:
                    # recorded after window admission: interval timing runs
                    # at completion pace under backpressure (no added sync)
                    d = getattr(data_batch, "data", None)
                    _telem.record_step(int(d[0].shape[0]) if d else 0,
                                       source="module")
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    _invoke_callbacks(batch_end_callback,
                                      BatchEndParam(epoch, nbatch, eval_metric))
                nbatch += 1
            # epoch-boundary drain point: retire every in-flight step
            # before the (syncing) metric read and the epoch callbacks
            window.drain()  # mxlint: disable=sync-in-loop
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            if epoch_end_callback is not None:
                arg_params, aux_params = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_params, aux_params)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

    def install_monitor(self, mon):
        raise NotImplementedError()

    # -- checkpointing (one key format, defined in model.py) -----------------
    def save_params(self, fname):
        from ..model import save_params_file
        arg_params, aux_params = self.get_params()
        save_params_file(fname, arg_params, aux_params)

    def load_params(self, fname):
        from ..model import load_params as _load
        arg_params, aux_params = _load(fname)
        self.set_params(arg_params, aux_params)


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _invoke_callbacks(callbacks, param):
    for cb in _as_list(callbacks):
        cb(param)
