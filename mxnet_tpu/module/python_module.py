"""PythonModule (reference python/mxnet/module/python_module.py): a module
whose compute is arbitrary Python — for loss layers/metrics that don't need
parameters. Subclass and override forward/backward."""
from __future__ import annotations

import logging

from .base_module import BaseModule


class PythonModule(BaseModule):
    def __init__(self, data_names, label_names, output_names, logger=None):
        super().__init__(logger=logger or logging)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._outputs = None

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        self.binded = True
        self.for_training = for_training
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        self.params_initialized = True

    def get_params(self):
        return {}, {}

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    def update(self):
        pass

    def get_outputs(self, merge_multi_context=True):
        return self._outputs

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        if self._outputs is not None:
            eval_metric.update(labels, self._outputs)

    def install_monitor(self, mon):
        pass
