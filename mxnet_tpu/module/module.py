"""Module (reference python/mxnet/module/module.py:40).

One Symbol bound to one executor per context: a single-entry ctx list is
the common path (one jit-compiled graph), while a ctx LIST slices each
batch across per-context executors with summed gradients and parameter
broadcast — the reference DataParallelExecutorGroup semantics
(python/mxnet/module/executor_group.py:144). The TPU-native path for real
multi-chip training remains parallel.DataParallelTrainer (one jit over a
mesh); this legacy path exists so ported multi-device Module scripts run
correctly instead of silently training on context[0].
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..base import MXNetError
from ..context import Context, current_context
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import optimizer as opt_mod
from ..initializer import InitDesc
from .base_module import BaseModule


def _default_rescale_grad(data_shapes, kvstore):
    """reference module.py:503-518: Module-owned optimizers default
    rescale_grad to 1/batch_size (x num_workers under dist_sync) —
    output-op gradients (SoftmaxOutput & co) are batch-SUMMED, so without
    this every standard lr diverges."""
    import os
    batch_size = data_shapes[0][1][0] if data_shapes else 1
    kv_type = kvstore if isinstance(kvstore, str) \
        else getattr(kvstore, "type", "")
    if kv_type and "dist" in kv_type and "_sync" in kv_type:
        if not isinstance(kvstore, str):
            batch_size *= kvstore.num_workers
        else:
            # env read + (guarded) process_count, not a throwaway
            # KVStoreDist — instantiating one here would parse the cluster
            # env and build allreduce state just to ask its size. Mirrors
            # KVStoreDist.num_workers = max(env size, jax.process_count()),
            # but only reads process_count when the distributed client is
            # already up: calling it cold would initialize the XLA backend
            # and forbid a later jax.distributed.initialize (the hazard
            # kvstore.py:334-337 documents)
            from .._dist_util import dist_client_active
            n_proc = 1
            if dist_client_active():
                import jax as _jax
                n_proc = _jax.process_count()
            batch_size *= max(1, int(os.environ.get(
                "MXNET_TPU_NUM_WORKERS",
                os.environ.get("DMLC_NUM_WORKER", "1"))), n_proc)
    return 1.0 / max(batch_size, 1)


def _shapes_dict(*shape_lists):
    """Normalize (name, shape) tuples / DataDesc objects into one dict —
    the single place bind() and output_shapes parse descriptors."""
    out = {}
    for descs in shape_lists:
        for desc in descs or []:
            name, shape = (desc[0], desc[1]) \
                if isinstance(desc, (tuple, list)) \
                else (desc.name, desc.shape)
            out[name] = tuple(shape)
    return out


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=None, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        import logging
        super().__init__(logger=logger or logging)
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        # ctx list -> batch-slicing data parallelism over one executor per
        # context (reference DataParallelExecutorGroup,
        # python/mxnet/module/executor_group.py:144): inputs are sliced
        # along axis 0, gradients are summed across executors before the
        # update, updated params are broadcast back.
        if isinstance(context, Context):
            self._contexts = [context]
        elif isinstance(context, (list, tuple)) and context:
            self._contexts = list(context)
        else:
            self._contexts = [current_context()]
        self._context = self._contexts[0]
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._arg_params: Dict[str, NDArray] = {}
        self._aux_params: Dict[str, NDArray] = {}
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._data_shapes = None
        self._label_shapes = None
        self._inputs_need_grad = False

    # -- binding -------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        shapes = _shapes_dict(data_shapes, label_shapes)
        self._data_shapes, self._label_shapes = data_shapes, label_shapes
        self._inferred_output_shapes = None
        req = grad_req if for_training else "null"
        if for_training:
            # params get gradients; data/labels only if inputs_need_grad
            req = {n: grad_req if (n in self._param_names
                                   or (inputs_need_grad and n in self._data_names))
                   else "null"
                   for n in self._symbol.list_arguments()}
        n_ctx = len(self._contexts)
        if n_ctx > 1:
            # per-executor shapes: batch axis 0 sliced evenly (the reference
            # additionally supports uneven work_load_list splits; we refuse)
            io_names = set(self._data_names) | set(self._label_names)
            sliced = {}
            for name, shape in shapes.items():
                if name in io_names:
                    if shape[0] % n_ctx != 0:
                        raise MXNetError(
                            f"batch dim {shape[0]} of '{name}' must divide "
                            f"evenly across {n_ctx} contexts")
                    sliced[name] = (shape[0] // n_ctx,) + tuple(shape[1:])
                else:
                    sliced[name] = shape
            self._execs = [self._symbol.simple_bind(ctx=c, grad_req=req,
                                                    **sliced)
                           for c in self._contexts]
        else:
            self._execs = [self._symbol.simple_bind(ctx=self._context,
                                                    grad_req=req, **shapes)]
        self._exec = self._execs[0]
        # cache the name->grad mapping once: list_arguments/grad_arrays are
        # full-graph traversals, too slow for the per-batch update() loop
        arg_names_all = self._symbol.list_arguments()
        self._exec_grads = [dict(zip(arg_names_all, e.grad_arrays))
                            for e in self._execs]
        self._exec_args = [dict(zip(arg_names_all, e.arg_arrays))
                           for e in self._execs]
        grads = self._exec_grads[0]
        self._param_grads = [(i, name, grads.get(name))
                             for i, name in enumerate(self._param_names)]
        self._data_grads = [grads.get(n) for n in self._data_names]
        self.binded = True
        self.for_training = for_training
        self._inputs_need_grad = inputs_need_grad
        if shared_module is not None and shared_module.params_initialized:
            ap, xp = shared_module.get_params()
            self.set_params(ap, xp)

    # -- parameters ----------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        assert self.binded, "call bind before init_params"
        if self.params_initialized and not force_init:
            return
        if arg_params is None and getattr(self, "_preloaded_params", None):
            # Module.load(): pull the checkpoint saved next to the symbol
            from ..model import load_params as _load
            arg_params, aux_params = _load(self._preloaded_params)
        from ..initializer import Uniform
        have_given = arg_params is not None
        if initializer is None and not have_given:
            initializer = Uniform(0.01)
        arg_dict = dict(zip(self._symbol.list_arguments(),
                            self._exec.arg_arrays))
        aux_dict = dict(zip(self._aux_names, self._exec.aux_arrays))
        for name in self._param_names:
            arr = arg_dict[name]
            if have_given and name in arg_params:
                arr._set_data(arg_params[name]._data.astype(arr.dtype))
            elif have_given and not allow_missing:
                raise MXNetError(
                    f"parameter '{name}' missing from given arg_params "
                    "(pass allow_missing=True to initialize it instead)")
            elif initializer is not None:
                initializer(InitDesc(name), arr)
            elif have_given:
                pass  # allow_missing with no initializer: keep current value
            else:
                raise MXNetError(f"no initializer and no value for {name}")
        for name in self._aux_names:
            arr = aux_dict[name]
            if aux_params is not None and name in aux_params:
                arr._set_data(aux_params[name]._data.astype(arr.dtype))
        self._arg_params = {n: arg_dict[n] for n in self._param_names}
        self._aux_params = dict(aux_dict)
        # replica executors start from the primary's values (reference
        # executor_group broadcast); aux states then evolve per replica and
        # get_params reads the primary's, like the reference's devices[0]
        for e, rep_args in zip(self._execs[1:], self._exec_args[1:]):
            for name in self._param_names:
                self._arg_params[name].copyto(rep_args[name])
            rep_aux = dict(zip(self._aux_names, e.aux_arrays))
            for name in self._aux_names:
                self._aux_params[name].copyto(rep_aux[name])
        self.params_initialized = True

    def get_params(self):
        assert self.params_initialized
        arg = {n: a.copy() if hasattr(a, "copy") else a
               for n, a in self._arg_params.items()}
        aux = {n: a.copy() if hasattr(a, "copy") else a
               for n, a in self._aux_params.items()}
        return arg, aux

    # -- optimizer -----------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        rescale_grad = _default_rescale_grad(self._data_shapes, kvstore)
        if isinstance(optimizer, opt_mod.Optimizer):
            if abs(optimizer.rescale_grad - rescale_grad) > 1e-12:
                import warnings
                warnings.warn(
                    "Optimizer created manually outside Module but "
                    f"rescale_grad is not 1/batch_size ({optimizer.rescale_grad}"
                    f" vs {rescale_grad}). Is this intended?", stacklevel=2)
            self._optimizer = optimizer
        else:
            params = dict(optimizer_params or ())
            params.setdefault("rescale_grad", rescale_grad)
            self._optimizer = opt_mod.create(optimizer, **params)
        self._updater = opt_mod.get_updater(self._optimizer)
        states_file = getattr(self, "_preloaded_states", None)
        if states_file is not None:
            with open(states_file, "rb") as f:
                self._updater.set_states(f.read())
        self.optimizer_initialized = True

    # -- compute -------------------------------------------------------------
    def _slice_for(self, arr, k):
        """Slice batch axis 0 for executor k and place on its context."""
        n = len(self._contexts)
        if arr.shape[0] % n != 0:
            raise MXNetError(
                f"batch dim {arr.shape[0]} must divide evenly across "
                f"{n} contexts (a short final batch needs padding — "
                "reference DataParallelExecutorGroup slices unevenly via "
                "work_load_list, which we deliberately do not)")
        m = arr.shape[0] // n
        part = arr[k * m:(k + 1) * m]
        return part.as_in_context(self._contexts[k])

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        if data_batch.label is not None and self._label_names:
            for name, arr in zip(self._label_names, data_batch.label):
                feed[name] = arr
        if len(self._execs) == 1:
            # place batch data on the module's context (reference
            # executor_group _load_data as_in_context) — a no-op when the
            # iterator already produced arrays there
            ctx = self._contexts[0]
            self._exec.forward(is_train=is_train,
                               **{n_: a.as_in_context(ctx)
                                  for n_, a in feed.items()})
            return
        for k, e in enumerate(self._execs):
            e.forward(is_train=is_train,
                      **{n_: self._slice_for(a, k) for n_, a in feed.items()})

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        # reference BaseModule.backward asserts for_training — an
        # inference bind has kNullOp grads, so a silent no-op here would
        # hide a training loop running on a for_training=False module
        assert self.for_training, \
            "backward() on a module bound with for_training=False"
        if len(self._execs) == 1:
            self._exec.backward(out_grads=out_grads)
            return
        for k, e in enumerate(self._execs):
            og = None
            if out_grads is not None:
                og = [self._slice_for(g, k) for g in out_grads]
            e.backward(out_grads=og)

    def update(self):
        assert self.optimizer_initialized
        multi = len(self._execs) > 1
        for i, name, g in self._param_grads:
            if g is None or name in self._fixed_param_names:
                continue
            if multi:
                # sum the replica gradients onto the primary context
                # (reference kvstore-local reduce semantics)
                for eg in self._exec_grads[1:]:
                    g = g + eg[name].as_in_context(self._context)
            self._updater(i, g, self._arg_params[name])
        if multi:
            # broadcast updated params back to the replica executors
            for arg_dict in self._exec_args[1:]:
                for name in self._param_names:
                    self._arg_params[name].copyto(arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        if len(self._execs) == 1:
            return self._exec.outputs
        if not merge_multi_context:
            # reference executor_group semantics: per-output list of
            # per-device arrays, so every batch slice stays reachable
            return [list(outs) for outs in zip(*(e.outputs
                                                 for e in self._execs))]
        merged = []
        for outs in zip(*(e.outputs for e in self._execs)):
            parts = [o.as_in_context(self._context) for o in outs]
            merged.append(nd.concat(*parts, dim=0))
        return merged

    def get_input_grads(self, merge_multi_context=True):
        assert self._inputs_need_grad
        if len(self._execs) == 1:
            return list(self._data_grads)
        if not merge_multi_context:
            return [[eg[name] for eg in self._exec_grads]
                    for name in self._data_names]
        merged = []
        for name in self._data_names:
            parts = [eg[name].as_in_context(self._context)
                     for eg in self._exec_grads]
            merged.append(nd.concat(*parts, dim=0))
        return merged

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self.get_outputs())

    def install_monitor(self, mon):
        mon.install(self._exec)

    # -- checkpoint (reference module.py save_checkpoint/load) ---------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._symbol.save("%s-symbol.json" % prefix)
        param_name = "%s-%04d.params" % (prefix, epoch)
        self.save_params(param_name)
        if save_optimizer_states and self._updater is not None:
            with open("%s-%04d.states" % (prefix, epoch), "wb") as f:
                f.write(self._updater.get_states())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from .. import symbol as sym_mod
        symbol = sym_mod.load("%s-symbol.json" % prefix)
        mod = Module(symbol, **kwargs)
        # consumed by init_params / init_optimizer after bind
        mod._preloaded_params = "%s-%04d.params" % (prefix, epoch)
        if load_optimizer_states:
            mod._preloaded_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        outs = self._exec.outputs
        if outs and len(self._execs) > 1:
            return [(name, (o.shape[0] * len(self._execs),) + tuple(o.shape[1:]))
                    for name, o in zip(self.output_names, outs)]
        if outs:
            return list(zip(self.output_names, [o.shape for o in outs]))
        # before the first forward the executor has no materialized
        # outputs — infer from the bound input shapes (the reference
        # exposes output_shapes right after bind; SequentialModule.bind
        # wires the next stage's inputs from them). Cached: infer_shape
        # walks the whole graph and the result is fixed for a bound
        # module.
        if getattr(self, "_inferred_output_shapes", None) is None:
            shapes = _shapes_dict(self._data_shapes, self._label_shapes)
            _, out_shapes, _ = self._symbol.infer_shape(**shapes)
            self._inferred_output_shapes = list(
                zip(self.output_names, out_shapes))
        return self._inferred_output_shapes
