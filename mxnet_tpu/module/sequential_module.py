"""SequentialModule (reference python/mxnet/module/sequential_module.py):
chains modules, each consuming the previous one's outputs."""
from __future__ import annotations

import logging

from .base_module import BaseModule


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=None):
        super().__init__(logger=logger or logging)
        self._modules = []
        self._metas = []
        self._label_shapes = None

    def add(self, module, **kwargs):
        self._modules.append(module)
        self._metas.append(kwargs)
        return self

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        assert shared_module is None
        self._label_shapes = label_shapes
        cur_shapes = data_shapes
        for i, (module, meta) in enumerate(zip(self._modules, self._metas)):
            take_labels = meta.get(self.META_TAKE_LABELS, False)
            need_grad = inputs_need_grad if i == 0 else True
            module.bind(cur_shapes,
                        label_shapes if take_labels else None,
                        for_training=for_training,
                        inputs_need_grad=need_grad,
                        force_rebind=force_rebind, grad_req=grad_req)
            outs = module.output_shapes
            # key the next module's input shapes by the NEXT module's own
            # data names (its symbol's free variables), not this module's
            if i + 1 < len(self._modules):
                next_names = getattr(self._modules[i + 1], "data_names",
                                     ["data"])
                cur_shapes = [(next_names[j] if j < len(next_names) else name,
                               shape)
                              for j, (name, shape) in enumerate(outs)]
        self.binded = True
        self.for_training = for_training

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        for module in self._modules:
            module.init_params(initializer=initializer, arg_params=arg_params,
                               aux_params=aux_params,
                               allow_missing=True, force_init=force_init)
        self.params_initialized = True

    def get_params(self):
        arg, aux = {}, {}
        for module in self._modules:
            a, x = module.get_params()
            arg.update(a)
            aux.update(x)
        return arg, aux

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        from ..io.io import DataBatch
        batch = data_batch
        for i, module in enumerate(self._modules):
            module.forward(batch, is_train=is_train)
            if i < len(self._modules) - 1:
                batch = DataBatch(data=module.get_outputs(),
                                  label=data_batch.label)

    def backward(self, out_grads=None):
        for i, module in reversed(list(enumerate(self._modules))):
            module.backward(out_grads=out_grads)
            if i > 0:
                out_grads = module.get_input_grads()

    def update(self):
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        for module, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels)
                return
        self._modules[-1].update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for module in self._modules:
            module.install_monitor(mon)
