"""BucketingModule (reference python/mxnet/module/bucketing_module.py:40).

Variable-length sequence training: one Module per bucket key, parameters
shared across buckets. On TPU each bucket is one jit signature — exactly the
reference's executor-per-bucket sharing, with XLA compile caches standing in
for shared memory pools.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module


class BucketingModule(BaseModule):
    def __init__(self, sym_gen: Callable, default_bucket_key=None,
                 logger=None, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger or logging)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets: Dict = {}
        self._curr_module: Module = None
        self._curr_bucket_key = None
        self._grad_req = "write"
        self._opt_args = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        return self._curr_module.symbol

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self._grad_req = grad_req
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                    force_rebind=False, grad_req=grad_req)
        self._buckets[self._default_bucket_key] = module
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True
        self.for_training = for_training

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """(bucketing_module.py:404)"""
        assert self.binded, "call bind before switching buckets"
        if bucket_key == self._curr_bucket_key:
            return  # common case: consecutive batches share a bucket
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self.for_training,
                        grad_req=self._grad_req)
            if self.params_initialized:
                # seed from the ACTIVE module — it holds the trained params
                ap, xp = self._curr_module.get_params()
                module.init_params(arg_params=ap, aux_params=xp,
                                   allow_missing=True, force_init=True)
                if self._opt_args is not None:
                    self._init_module_optimizer(module)
            if getattr(self, "_monitor", None) is not None:
                module.install_monitor(self._monitor)
            self._buckets[bucket_key] = module
        else:
            module = self._buckets[bucket_key]
            if self.params_initialized:
                # pull latest shared params from the previously-active bucket
                ap, xp = self._curr_module.get_params()
                module.init_params(arg_params=ap, aux_params=xp,
                                   allow_missing=True, force_init=True)
        self._curr_module = module
        self._curr_bucket_key = bucket_key

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        assert self.binded
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init)
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        from .. import optimizer as opt_mod
        # ONE optimizer + updater shared across buckets: momentum/Adam state
        # and update counts must not fork per jit signature
        if isinstance(optimizer, opt_mod.Optimizer):
            self._shared_optimizer = optimizer
        else:
            # same 1/batch_size default the child Modules would apply
            # (module.py _default_rescale_grad) — the shared optimizer is
            # handed to them pre-built, so the default must land here
            from .module import _default_rescale_grad
            params = dict(optimizer_params or ())
            params.setdefault("rescale_grad", _default_rescale_grad(
                getattr(self._curr_module, "_data_shapes", None), kvstore))
            self._shared_optimizer = opt_mod.create(optimizer, **params)
        self._shared_updater = opt_mod.get_updater(self._shared_optimizer)
        self._opt_args = dict(kvstore=kvstore)
        for mod in self._buckets.values():
            self._init_module_optimizer(mod, force_init=force_init)
        self.optimizer_initialized = True

    def _init_module_optimizer(self, mod, force_init=False):
        mod.init_optimizer(kvstore=self._opt_args.get("kvstore", "local"),
                           optimizer=self._shared_optimizer,
                           force_init=force_init)
        mod._updater = self._shared_updater

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        # DataBatch always HAS these attributes (default None) — test the
        # values, not attribute presence
        bucket_key = getattr(data_batch, "bucket_key", None)
        if bucket_key is None:
            bucket_key = self._default_bucket_key
        shapes = getattr(data_batch, "provide_data", None)
        if shapes is not None:
            # the batch describes itself: take its label shapes verbatim —
            # None means an unlabeled batch, NOT "reuse the current bucket's"
            label_shapes = getattr(data_batch, "provide_label", None)
        else:
            shapes = self._curr_module.data_shapes
            label_shapes = self._curr_module.label_shapes
        self.switch_bucket(bucket_key, shapes, label_shapes)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        if not self._curr_module.optimizer_initialized and self._opt_args:
            self._init_module_optimizer(self._curr_module)
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        self._monitor = mon  # also installed on buckets created later
        for mod in self._buckets.values():
            mod.install_monitor(mon)
