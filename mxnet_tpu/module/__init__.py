"""Legacy symbolic trainer API (reference python/mxnet/module/).

`Module` binds a Symbol into a jit-compiled Executor and drives the classic
fit/forward/backward/update loop (reference module/base_module.py:409 fit,
module/module.py:40 Module). `BucketingModule` keeps one Executor per bucket
key — on TPU each bucket is its own jit signature, which is exactly the
reference's per-bucket executor sharing (bucketing_module.py:40).
`SequentialModule` chains modules (sequential_module.py).
"""
from .base_module import BaseModule
from .module import Module
from .bucketing_module import BucketingModule
from .sequential_module import SequentialModule
from .python_module import PythonModule

__all__ = ["BaseModule", "Module", "BucketingModule", "SequentialModule",
           "PythonModule"]
