"""mx.np.random (reference python/mxnet/numpy/random.py over _npi_ samplers).

Counter-based: draws consume keys from the framework RNG stream
(mxnet_tpu.random), the TPU-native replacement for the reference's
per-device random_generator.h state.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import random as _rng
from . import _wrap, _raw_in


def _key():
    # typed key (next_key): the supported jax.random form; next_key_raw is
    # only for shipping key data across op/jit boundaries
    return _rng.next_key()


def _shape(size):
    # None passes through: jax.random broadcasts to the params' shape, which
    # matches NumPy's size=None semantics for array-valued parameters
    if size is None:
        return None
    if isinstance(size, int):
        return (size,)
    return tuple(size)


def seed(s):
    _rng.seed(s)


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None):
    low, high = _raw_in(low), _raw_in(high)
    shp = _shape(size)
    if shp is None:
        shp = jnp.broadcast_shapes(jnp.shape(low), jnp.shape(high))
    out = jax.random.uniform(_key(), shp,
                             dtype=jnp.dtype(dtype) if dtype else jnp.float32,
                             minval=low, maxval=high)
    return _wrap(out, ctx)


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    loc, scale = _raw_in(loc), _raw_in(scale)
    shp = _shape(size)
    if shp is None:
        shp = jnp.broadcast_shapes(jnp.shape(loc), jnp.shape(scale))
    out = jax.random.normal(_key(), shp,
                            dtype=jnp.dtype(dtype) if dtype else jnp.float32)
    return _wrap(out * scale + loc, ctx)


def randn(*size):
    return normal(size=size or None)


def rand(*size):
    return uniform(size=size or None)


def randint(low, high=None, size=None, dtype=None, ctx=None):
    if high is None:
        low, high = 0, low
    shp = _shape(size)
    out = jax.random.randint(_key(), shp if shp is not None else (), _raw_in(low), _raw_in(high),
                             dtype=jnp.dtype(dtype) if dtype else jnp.int32)
    return _wrap(out, ctx)


def choice(a, size=None, replace=True, p=None, ctx=None):
    a_raw = _raw_in(a) if not isinstance(a, int) else jnp.arange(a)
    p_raw = _raw_in(p) if p is not None else None
    out = jax.random.choice(_key(), a_raw, _shape(size), replace=replace,
                            p=p_raw)
    return _wrap(out, ctx)


def permutation(x):
    if isinstance(x, int):
        return _wrap(jax.random.permutation(_key(), x))
    return _wrap(jax.random.permutation(_key(), _raw_in(x)))


def shuffle(x):
    """In-place shuffle along axis 0 (reference _npi_shuffle)."""
    x._set_data(jax.random.permutation(_key(), x._data))


def exponential(scale=1.0, size=None, ctx=None):
    shp = _shape(size)
    out = jax.random.exponential(_key(), shp if shp is not None else ())
    return _wrap(out * _raw_in(scale), ctx)


def gamma(shape, scale=1.0, size=None, ctx=None):
    out = jax.random.gamma(_key(), _raw_in(shape), _shape(size)) * _raw_in(scale)
    return _wrap(out, ctx)


def beta(a, b, size=None, ctx=None):
    return _wrap(jax.random.beta(_key(), _raw_in(a), _raw_in(b), _shape(size)), ctx)


def chisquare(df, size=None, ctx=None):
    return _wrap(jax.random.chisquare(_key(), _raw_in(df), shape=_shape(size)), ctx)


def multinomial(n, pvals, size=None):
    pv = _raw_in(pvals)
    shp = (_shape(size) or ()) + (pv.shape[-1],)
    out = jax.random.multinomial(_key(), n, pv, shape=shp if size else None)
    return _wrap(out)


def multivariate_normal(mean, cov, size=None, ctx=None):
    out = jax.random.multivariate_normal(_key(), _raw_in(mean), _raw_in(cov),
                                         _shape(size) or None)
    return _wrap(out, ctx)


def laplace(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    shp = _shape(size)
    out = jax.random.laplace(_key(), shp if shp is not None else (),
                             dtype=jnp.dtype(dtype) if dtype else jnp.float32)
    return _wrap(out * _raw_in(scale) + _raw_in(loc), ctx)


def lognormal(mean=0.0, sigma=1.0, size=None, ctx=None):
    return _wrap(jnp.exp(jax.random.normal(_key(), _shape(size) if _shape(size) is not None else ()) * _raw_in(sigma) + _raw_in(mean)), ctx)


def logistic(loc=0.0, scale=1.0, size=None, ctx=None):
    return _wrap(jax.random.logistic(_key(), _shape(size) if _shape(size) is not None else ()) * scale + loc, ctx)


def pareto(a, size=None, ctx=None):
    return _wrap(jax.random.pareto(_key(), _raw_in(a), shape=_shape(size)) - 1.0, ctx)


def poisson(lam=1.0, size=None, ctx=None):
    return _wrap(jax.random.poisson(_key(), _raw_in(lam), shape=_shape(size)), ctx)


def weibull(a, size=None, ctx=None):
    return _wrap(jax.random.weibull_min(_key(), 1.0, _raw_in(a), shape=_shape(size)), ctx)


def gumbel(loc=0.0, scale=1.0, size=None, ctx=None):
    return _wrap(jax.random.gumbel(_key(), _shape(size) if _shape(size) is not None else ()) * scale + loc, ctx)


def rayleigh(scale=1.0, size=None, ctx=None):
    return _wrap(jax.random.rayleigh(_key(), shape=_shape(size)) * _raw_in(scale), ctx)
