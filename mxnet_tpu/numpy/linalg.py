"""mx.np.linalg (reference python/mxnet/numpy/linalg.py + src/operator/numpy/
linalg/). Delegates to jax.numpy.linalg with tape-aware wrapping."""
from __future__ import annotations

import jax.numpy as jnp

from . import _apply, _make_fn

_DELEGATED = ["norm", "svd", "cholesky", "qr", "inv", "pinv", "det", "slogdet",
              "solve", "lstsq", "eig", "eigh", "eigvals", "eigvalsh",
              "matrix_rank", "matrix_power", "multi_dot", "tensorinv",
              "tensorsolve", "cond"]

_g = globals()
for _name in _DELEGATED:
    _j = getattr(jnp.linalg, _name, None)
    if _j is not None:
        _g[_name] = _make_fn(_j, _name)

__all__ = [n for n in _DELEGATED if n in _g]
