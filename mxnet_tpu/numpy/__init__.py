"""``mx.np`` — the NumPy-semantics array API.

Reference: python/mxnet/numpy/multiarray.py (294 defs over _npi_* C++ ops,
SURVEY.md §2.2 numpy/ 70 files / 16.9 kLoC). TPU-native design: jax.numpy
IS the NumPy-compatible compute layer, so every function here is a thin
autograd-aware delegation to jnp — one `_apply` path that mirrors
ndarray.invoke (jax.vjp + tape record) instead of 70 files of kernels. The
`ndarray` class is a zero-slot subclass of the imperative NDArray, so
mx.np arrays ride the same tape, context, and serialization machinery.
"""
from __future__ import annotations

import builtins as _bi
from typing import Any, Optional, Sequence

import numpy as _onp
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray, _track

__all__ = ["ndarray"]  # extended programmatically below


class ndarray(NDArray):
    """mx.np.ndarray (reference numpy/multiarray.py:77)."""
    __slots__ = ()

    def __repr__(self):
        return f"array({self.asnumpy()!r})".replace("array(array", "array(")

    # Arithmetic follows NUMPY promotion rules (true division, weak-type
    # scalar promotion) — NOT the legacy nd semantics where the scalar is
    # cast to the tensor dtype (int32/2 == 0 there). Routed through _apply
    # so the autograd tape records.
    def _np_bin(self, other, jfn, reverse=False):
        a, b = (other, self) if reverse else (self, other)
        return _apply(jfn, (a, b), {})

    def __add__(self, o): return self._np_bin(o, jnp.add)
    def __radd__(self, o): return self._np_bin(o, jnp.add, True)
    def __sub__(self, o): return self._np_bin(o, jnp.subtract)
    def __rsub__(self, o): return self._np_bin(o, jnp.subtract, True)
    def __mul__(self, o): return self._np_bin(o, jnp.multiply)
    def __rmul__(self, o): return self._np_bin(o, jnp.multiply, True)
    def __truediv__(self, o): return self._np_bin(o, jnp.true_divide)
    def __rtruediv__(self, o): return self._np_bin(o, jnp.true_divide, True)
    def __floordiv__(self, o): return self._np_bin(o, jnp.floor_divide)
    def __rfloordiv__(self, o): return self._np_bin(o, jnp.floor_divide, True)
    def __mod__(self, o): return self._np_bin(o, jnp.mod)
    def __rmod__(self, o): return self._np_bin(o, jnp.mod, True)
    def __pow__(self, o): return self._np_bin(o, jnp.power)
    def __rpow__(self, o): return self._np_bin(o, jnp.power, True)

    # numpy-style methods delegate to module functions
    def mean(self, axis=None, dtype=None, keepdims=False):
        return mean(self, axis=axis, dtype=dtype, keepdims=keepdims)

    def sum(self, axis=None, dtype=None, keepdims=False):
        return sum(self, axis=axis, dtype=dtype, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return max(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return min(self, axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return prod(self, axis=axis, keepdims=keepdims)

    def std(self, axis=None, ddof=0, keepdims=False):
        return std(self, axis=axis, ddof=ddof, keepdims=keepdims)

    def var(self, axis=None, ddof=0, keepdims=False):
        return var(self, axis=axis, ddof=ddof, keepdims=keepdims)

    def argmax(self, axis=None):
        return argmax(self, axis=axis)

    def argmin(self, axis=None):
        return argmin(self, axis=axis)

    def cumsum(self, axis=None):
        return cumsum(self, axis=axis)

    def flatten(self, order="C"):
        return reshape(self, (-1,))

    def item(self):
        return self.asnumpy().item()

    def tolist(self):
        return self.asnumpy().tolist()

    def copy(self):
        # through _apply so the tape records (identity vjp)
        return _apply(lambda x: jnp.array(x, copy=True), (self,), {})

    def astype(self, dtype, copy=True):
        dt = jnp.dtype(dtype)
        if not copy and self._data.dtype == dt:
            return self
        return _apply(lambda x: x.astype(dt), (self,), {})

    def reshape(self, *shape, order="C"):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return reshape(self, shape)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return transpose(self, axes if axes else None)

    def squeeze(self, axis=None):
        return squeeze(self, axis=axis)

    def ravel(self):
        return reshape(self, (-1,))

    def clip(self, a_min=None, a_max=None):
        return clip(self, a_min, a_max)

    def round(self, decimals=0):
        return around(self, decimals=decimals)

    def dot(self, other):
        return dot(self, other)

    def as_nd_ndarray(self):
        out = NDArray(self._data, self._ctx)
        out._ag_node = self._ag_node
        return out

    def as_np_ndarray(self):
        return self


def _wrap(raw, ctx=None) -> ndarray:
    out = ndarray(raw, ctx or current_context())
    _track(out)
    return out


def _raw_in(x):
    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (int, float, bool, complex)):
        return x
    return jnp.asarray(x)


def _apply(jfn, args, kwargs):
    """Autograd-aware delegation (mirrors ndarray.invoke): runs jfn on the
    raw arrays; when the tape is recording and an input is attached, computes
    via jax.vjp and records."""
    from .. import autograd
    # NDArrays may appear as positional args, inside a list/tuple arg
    # (concatenate/stack take sequences), or as keyword args (indices=,
    # condition=, …); flatten all three into the vjp inputs
    entries = []  # (arg_pos | kw_name, elem_pos | None)
    for i, a in enumerate(args):
        if isinstance(a, NDArray):
            entries.append((i, None))
        elif isinstance(a, (list, tuple)):
            for j, e in enumerate(a):
                if isinstance(e, NDArray):
                    entries.append((i, j))
    for k, a in kwargs.items():
        if isinstance(a, NDArray):
            entries.append((k, None))

    def _get(i, j):
        src = kwargs[i] if isinstance(i, str) else args[i]
        return src if j is None else src[j]

    ins = [_get(i, j) for i, j in entries]
    raws = [x._data for x in ins]
    # NB: use builtins explicitly — this module shadows any/all/sum/min/max
    need = (autograd.is_recording()
            and _bi.any(x._ag_node is not None for x in ins))

    def fn(*arrs):
        # only NDArray positions are substituted; every other arg
        # (None, shape tuples, scalars, python lists) passes through verbatim
        full = [list(x) if isinstance(x, (list, tuple)) else x for x in args]
        kw = dict(kwargs)
        for (i, j), r in zip(entries, arrs):
            if isinstance(i, str):
                kw[i] = r
            elif j is None:
                full[i] = r
            else:
                full[i][j] = r
        return jfn(*full, **kw)

    if need:
        try:
            outs_raw, vjp_fn = jax.vjp(fn, *raws)
        except TypeError:  # non-differentiable output (int/bool)
            outs_raw, vjp_fn, need = fn(*raws), None, False
    else:
        outs_raw, vjp_fn = fn(*raws), None
    was_tuple = isinstance(outs_raw, (tuple, list))
    outs_t = tuple(outs_raw) if was_tuple else (outs_raw,)
    if need and not _bi.any(jnp.issubdtype(o.dtype, jnp.inexact) for o in outs_t):
        need = False  # integer outputs carry no gradient
    ctx = ins[0]._ctx if ins else current_context()
    outs = [_wrap(o, ctx) for o in outs_t]
    if need:
        autograd.record_op(vjp_fn, ins, outs, out_is_tuple=was_tuple, refn=fn)
    if was_tuple:
        return list(outs)
    return outs[0]


def _make_fn(jfn, name):
    def wrapper(*args, **kwargs):
        out = kwargs.pop("out", None)
        res = _apply(jfn, args, kwargs)
        if out is not None:
            out._set_data(res._data)
            out._ag_node = res._ag_node
            return out
        return res
    wrapper.__name__ = name
    wrapper.__qualname__ = name
    wrapper.__doc__ = (getattr(jfn, "__doc__", "") or "")[:400] + \
        f"\n\n(mx.np.{name} — NumPy-semantics op, delegates to jax.numpy)"
    return wrapper


# Everything in this list delegates 1:1 to jax.numpy (same names/semantics).
_DELEGATED = [
    # math / ufuncs
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "mod", "remainder", "fmod", "power", "float_power", "negative", "positive",
    "absolute", "abs", "fabs", "sign", "rint", "floor", "ceil", "trunc",
    "sqrt", "cbrt", "square", "reciprocal", "exp", "expm1", "exp2", "log",
    "log2", "log10", "log1p", "logaddexp", "logaddexp2", "sin", "cos", "tan",
    "arcsin", "arccos", "arctan", "arctan2", "sinh", "cosh", "tanh", "arcsinh",
    "arccosh", "arctanh", "hypot", "degrees", "radians", "deg2rad", "rad2deg",
    "maximum", "minimum", "fmax", "fmin", "heaviside", "gcd", "lcm", "ldexp",
    "around", "round", "clip", "nan_to_num", "real", "imag", "conj",  # noqa
    "conjugate", "i0", "sinc", "interp", "unwrap", "ediff1d", "trapz",
    "copysign", "frexp", "nextafter", "spacing",
    # comparison / logic
    "equal", "not_equal", "less", "less_equal", "greater", "greater_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not", "isnan",
    "isinf", "isposinf", "isneginf", "isfinite", "isclose", "allclose",
    "array_equal", "array_equiv", "signbit",
    # bitwise
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "invert",
    "left_shift", "right_shift",
    # reductions
    "sum", "prod", "mean", "std", "var", "min", "max", "amin", "amax", "ptp",
    "median", "quantile", "percentile", "average", "nansum", "nanprod",
    "nanmean", "nanstd", "nanvar", "nanmin", "nanmax", "nanmedian",
    "nanquantile", "nanpercentile", "all", "any", "count_nonzero", "argmin",
    "argmax", "nanargmin", "nanargmax", "cumsum", "cumprod", "nancumsum",
    "nancumprod",
    # shape manipulation
    "reshape", "ravel", "transpose", "swapaxes", "moveaxis", "rollaxis",
    "expand_dims", "squeeze", "concatenate", "stack", "vstack", "hstack",
    "dstack", "column_stack", "row_stack", "split", "array_split", "vsplit",
    "hsplit", "dsplit", "tile", "repeat", "flip", "fliplr", "flipud", "roll",
    "rot90", "pad", "broadcast_to", "broadcast_arrays", "atleast_1d",
    "atleast_2d", "atleast_3d", "flatnonzero", "resize", "append", "delete",
    "insert", "trim_zeros",
    # linear algebra / products
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum", "kron",
    "trace", "cross", "diagonal",
    # indexing / selection
    "where", "take", "take_along_axis", "choose", "compress", "diag",
    "diagflat", "tril", "triu", "extract", "select", "searchsorted", "nonzero",
    "argwhere", "unravel_index", "ravel_multi_index", "indices", "ix_",
    "diag_indices", "tril_indices", "triu_indices", "triu_indices_from",
    "tril_indices_from", "diag_indices_from", "put_along_axis",
    # sorting / sets
    "sort", "argsort", "lexsort", "partition", "argpartition", "unique",
    "intersect1d", "union1d", "setdiff1d", "setxor1d", "in1d", "isin",
    "sort_complex", "msort" if hasattr(jnp, "msort") else "sort",
    # statistics / histogram
    "histogram", "histogram2d", "histogramdd", "histogram_bin_edges",
    "bincount", "digitize", "corrcoef", "cov", "correlate", "convolve",
    # polynomials / misc
    "polyval", "polyfit", "polyadd", "polysub", "polymul", "polyder",
    "polyint", "vander", "gradient", "diff", "sinc", "meshgrid",
    "apply_along_axis", "tensordot", "float_power", "divmod",
    # window functions (reference _npi_blackman/_npi_hamming/_npi_hanning)
    "blackman", "hamming", "hanning", "bartlett", "kaiser",
]


def trapz(y, x=None, dx=1.0, axis=-1):
    """Trapezoidal integration (jnp renamed it trapezoid)."""
    fn = getattr(jnp, "trapezoid", None) or jnp.trapz
    return _apply(fn, (y,) if x is None else (y, x),
                  {"dx": dx, "axis": axis} if x is None else {"axis": axis})

_g = globals()
for _name in dict.fromkeys(_DELEGATED):
    _j = getattr(jnp, _name, None)
    if _j is None:
        continue
    _g[_name] = _make_fn(_j, _name)
    __all__.append(_name)


# ---------------------------------------------------------------------------
# creation functions (need ctx/dtype handling)
# ---------------------------------------------------------------------------

def array(obj, dtype=None, ctx=None):
    if isinstance(obj, NDArray):
        raw = obj._data
    else:
        raw = jnp.asarray(obj, dtype=jnp.dtype(dtype) if dtype else None)
    if dtype is not None:
        raw = raw.astype(jnp.dtype(dtype))
    elif raw.dtype == jnp.float64:
        raw = raw.astype(jnp.float32)
    return _wrap(raw, ctx)


def _creation(jfn, name):
    def wrapper(*args, dtype=None, ctx=None, **kwargs):
        if dtype is not None:
            kwargs["dtype"] = jnp.dtype(dtype)
        elif name not in ("arange", "eye", "identity"):
            kwargs["dtype"] = jnp.float32
        return _wrap(jfn(*args, **kwargs), ctx)
    wrapper.__name__ = name
    return wrapper


zeros = _creation(jnp.zeros, "zeros")
ones = _creation(jnp.ones, "ones")
empty = _creation(jnp.empty, "empty")
eye = _creation(jnp.eye, "eye")
identity = _creation(jnp.identity, "identity")
arange = _creation(jnp.arange, "arange")


def full(shape, fill_value, dtype=None, ctx=None):
    return _wrap(jnp.full(shape, fill_value,
                          dtype=jnp.dtype(dtype) if dtype else jnp.float32), ctx)


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None):
    out = jnp.linspace(start, stop, num, endpoint=endpoint, retstep=retstep,
                       dtype=jnp.dtype(dtype) if dtype else jnp.float32, axis=axis)
    if retstep:
        return _wrap(out[0], ctx), float(out[1])
    return _wrap(out, ctx)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None, ctx=None):
    return _wrap(jnp.logspace(start, stop, num, endpoint=endpoint, base=base,
                              dtype=jnp.dtype(dtype) if dtype else jnp.float32), ctx)


def zeros_like(a, dtype=None):
    return _wrap(jnp.zeros_like(_raw_in(a), dtype=dtype))


def ones_like(a, dtype=None):
    return _wrap(jnp.ones_like(_raw_in(a), dtype=dtype))


def full_like(a, fill_value, dtype=None):
    return _wrap(jnp.full_like(_raw_in(a), fill_value, dtype=dtype))


def empty_like(a, dtype=None):
    return _wrap(jnp.empty_like(_raw_in(a), dtype=dtype))


def copy(a):
    return _wrap(jnp.array(_raw_in(a), copy=True))


def asarray(a, dtype=None):
    return array(a, dtype=dtype)


def ascontiguousarray(a, dtype=None):
    return array(a, dtype=dtype)


def may_share_memory(a, b):
    return _raw_in(a) is _raw_in(b)


def shares_memory(a, b):
    return _raw_in(a) is _raw_in(b)


def shape(a):
    return tuple(_raw_in(a).shape)


def ndim(a):
    return _raw_in(a).ndim


def size(a, axis=None):
    r = _raw_in(a)
    return int(r.shape[axis]) if axis is not None else int(r.size)


def result_type(*args):
    return jnp.result_type(*[_raw_in(a) if not isinstance(a, (str, type))
                             else a for a in args])


def can_cast(from_, to):
    return jnp.can_cast(from_ if isinstance(from_, (str, type, jnp.dtype))
                        else _raw_in(from_).dtype, to)


def promote_types(t1, t2):
    return jnp.promote_types(t1, t2)


def expand_dims_(a, axis):
    return _apply(jnp.expand_dims, (a,), {"axis": axis})


# dtype aliases (reference numpy/__init__.py re-exports)
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
uint16 = _onp.uint16
uint32 = _onp.uint32
uint64 = _onp.uint64
bool_ = _onp.bool_
bfloat16 = jnp.bfloat16
pi = _onp.pi
e = _onp.e
euler_gamma = _onp.euler_gamma
inf = _onp.inf
nan = _onp.nan
newaxis = None
dtype = _onp.dtype
integer = _onp.integer
floating = _onp.floating
inexact = _onp.inexact
number = _onp.number

from . import random  # noqa: E402,F401
from . import linalg  # noqa: E402,F401

__all__ += ["array", "zeros", "ones", "empty", "full", "eye", "identity",
            "arange", "linspace", "logspace", "zeros_like", "ones_like",
            "full_like", "empty_like", "copy", "asarray", "shape", "ndim",
            "size", "random", "linalg", "newaxis", "pi", "inf", "nan"]


def fix(x, out=None):
    """Round toward zero (np.fix). Delegates to trunc — jnp.fix is
    deprecated (removed in jax 0.10) and truncation is the same op."""
    return trunc(x)
