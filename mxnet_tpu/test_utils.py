"""Test utilities (reference python/mxnet/test_utils.py, 2400 l).

Ports the reference's numeric test harness: dtype-aware assert_almost_equal,
finite-difference check_numeric_gradient (test_utils.py:981), cross-context
check_consistency (:1422 — CPU interpreter is the 'fake backend' reference
for the TPU, exactly like CPU-vs-GPU in the reference).
"""
from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

import numpy as _np

from .base import MXNetError
from .context import Context, cpu, current_context, tpu
from .ndarray import NDArray, array, zeros
from . import autograd

_DEFAULT_RTOL = {
    _np.dtype(_np.float16): 1e-2,
    _np.dtype(_np.float32): 1e-4,
    _np.dtype(_np.float64): 1e-12,
    "bfloat16": 2e-2,
}
_DEFAULT_ATOL = {
    _np.dtype(_np.float16): 1e-3,
    _np.dtype(_np.float32): 1e-5,
    _np.dtype(_np.float64): 1e-14,
    "bfloat16": 1e-2,
}


def default_context() -> Context:
    return current_context()


def set_default_context(ctx: Context):
    from . import context as ctx_mod
    ctx_mod._INITIAL_DEFAULT = ctx


def _as_np(x):
    if isinstance(x, NDArray):
        a = x.asnumpy()
    else:
        a = _np.asarray(x)
    if a.dtype.name == "bfloat16":
        a = a.astype(_np.float32)
    return a


def _tols(a, b, rtol, atol):
    def tol(tbl, arr):
        key = "bfloat16" if getattr(arr.dtype, "name", "") == "bfloat16" else arr.dtype
        return tbl.get(key, tbl[_np.dtype(_np.float32)])
    if rtol is None:
        rtol = max(tol(_DEFAULT_RTOL, a), tol(_DEFAULT_RTOL, b))
    if atol is None:
        atol = max(tol(_DEFAULT_ATOL, a), tol(_DEFAULT_ATOL, b))
    return rtol, atol


def same(a, b):
    return _np.array_equal(_as_np(a), _as_np(b))


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """reference test_utils.py:534."""
    a_raw = a if hasattr(a, "dtype") else _np.asarray(a)
    a, b = _as_np(a), _as_np(b)
    rtol, atol = _tols(a_raw, b, rtol, atol)
    if not _np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan):
        err = _np.abs(a - b)
        denom = _np.abs(b) + atol
        idx = _np.unravel_index(_np.argmax(err / denom), err.shape)
        raise AssertionError(
            f"{names[0]} and {names[1]} differ: max rel err "
            f"{(err / denom).max():.3e} at {idx}: {a[idx]} vs {b[idx]} "
            f"(rtol={rtol}, atol={atol})")


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    a2, b2 = _as_np(a), _as_np(b)
    rtol, atol = _tols(a if hasattr(a, "dtype") else a2, b2, rtol, atol)
    return _np.allclose(a2, b2, rtol=rtol, atol=atol, equal_nan=equal_nan)


def rand_ndarray(shape, dtype="float32", ctx=None, low=-1.0, high=1.0):
    a = _np.random.uniform(low, high, size=shape).astype("float32")
    return array(a, ctx=ctx, dtype=dtype)


def synthetic_cifar10(n=2048, seed=0, label_noise=0.08):
    """Deterministic CIFAR-class synthetic classification set with a
    built-in Bayes ceiling (reference tests use real CIFAR for the same
    purpose, e.g. example/image-classification/train_cifar10.py).

    Low-frequency per-class color templates (8x8 upsampled to 32x32, the
    spatial structure a conv net needs) + strong pixel noise, and
    `label_noise` of the labels re-rolled uniformly — so a perfectly
    trained model tops out around 1 - 0.9*label_noise, never 1.0. That
    headroom is what makes an int8-vs-fp32 accuracy-parity gate
    non-vacuous: on a saturated task both read 1.0 and any quantization
    bug passes.

    Returns (x, y): float32 (n, 3, 32, 32) in [0, ~2), float32 labels.
    """
    rng = _np.random.RandomState(seed)
    labs = rng.randint(0, 10, size=(n,))
    base8 = rng.rand(10, 3, 8, 8).astype("float32")
    base = _np.kron(base8, _np.ones((4, 4), "float32"))  # (10, 3, 32, 32)
    x = base[labs] * 0.9 + rng.rand(n, 3, 32, 32).astype("float32") * 1.1
    flip = rng.rand(n) < label_noise
    labs[flip] = rng.randint(0, 10, size=int(flip.sum()))
    return x.astype("float32"), labs.astype("float32")


def rand_shape_2d(dim0=10, dim1=10):
    return (_np.random.randint(1, dim0 + 1), _np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(_np.random.randint(1, d + 1) for d in (dim0, dim1, dim2))


def rand_shape_nd(ndim, dim=10):
    return tuple(_np.random.randint(1, dim + 1, size=ndim).tolist())


def check_numeric_gradient(fn: Callable[..., NDArray], inputs: List[NDArray],
                           eps: float = 1e-3, rtol: float = 1e-2,
                           atol: float = 1e-3, argnums: Optional[List[int]] = None):
    """Finite-difference vs autograd (reference test_utils.py:981).

    fn: NDArray... -> NDArray (scalar or any shape; summed internally).
    """
    argnums = argnums if argnums is not None else list(range(len(inputs)))
    for x in inputs:
        if x._ag_node is None:
            x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
        loss = out.sum()
    loss.backward()
    analytic = [inputs[i].grad.asnumpy().astype(_np.float64) for i in argnums]

    numeric = []
    for i in argnums:
        x = inputs[i]
        base = x.asnumpy().astype(_np.float64)
        g = _np.zeros_like(base)
        flat = base.reshape(-1)
        gf = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            x._set_data(array(base.reshape(x.shape), dtype=x.dtype)._data)
            fp = float(fn(*inputs).sum().asscalar())
            flat[j] = orig - eps
            x._set_data(array(base.reshape(x.shape), dtype=x.dtype)._data)
            fm = float(fn(*inputs).sum().asscalar())
            flat[j] = orig
            x._set_data(array(base.reshape(x.shape), dtype=x.dtype)._data)
            gf[j] = (fp - fm) / (2 * eps)
        numeric.append(g)

    for i, (an, nu) in enumerate(zip(analytic, numeric)):
        if not _np.allclose(an, nu, rtol=rtol, atol=atol):
            err = _np.abs(an - nu)
            idx = _np.unravel_index(_np.argmax(err), err.shape)
            raise AssertionError(
                f"numeric/analytic gradient mismatch for input {argnums[i]} at "
                f"{idx}: analytic={an[idx]:.6f} numeric={nu[idx]:.6f} "
                f"(max abs err {err.max():.3e})")
    return True


def check_consistency(fn: Callable[..., NDArray], inputs_np: List[_np.ndarray],
                      ctx_list: Optional[List[Context]] = None,
                      dtypes=("float32",), rtol=None, atol=None):
    """Run fn across contexts/dtypes and compare (reference :1422)."""
    from .context import num_tpus
    if ctx_list is None:
        ctx_list = [cpu()]
        if num_tpus():
            ctx_list.append(tpu())
    ref = None
    for ctx in ctx_list:
        for dt in dtypes:
            ins = [array(a, ctx=ctx, dtype=dt) for a in inputs_np]
            out = fn(*ins)
            outs = out if isinstance(out, (list, tuple)) else [out]
            res = [_as_np(o) for o in outs]
            if ref is None:
                ref = res
            else:
                for r, o in zip(ref, res):
                    assert_almost_equal(r, o, rtol=rtol, atol=atol,
                                        names=("ref", f"{ctx}/{dt}"))
    return True


@contextmanager
def environment(*args):
    """EnvManager parity (reference test_utils.py:2306): environment(k, v) or
    environment({k: v})."""
    if len(args) == 2:
        env_dict = {args[0]: args[1]}
    else:
        env_dict = dict(args[0])
    saved = {k: os.environ.get(k) for k in env_dict}
    try:
        for k, v in env_dict.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


EnvManager = environment


def assert_raises(exc, fn, *args, **kwargs):
    try:
        fn(*args, **kwargs)
    except exc:
        return
    raise AssertionError(f"{exc.__name__} not raised")


def discard_stderr(fn):
    return fn


def almost_equal_ignore_nan(a, b, rtol=None, atol=None):
    """Elementwise closeness ignoring positions where EITHER side is NaN
    (reference test_utils.py almost_equal_ignore_nan)."""
    a, b = _as_np(a).copy(), _as_np(b).copy()
    nan = _np.isnan(a) | _np.isnan(b)
    a[nan], b[nan] = 0, 0
    return almost_equal(a, b, rtol, atol)


def assert_almost_equal_ignore_nan(a, b, rtol=None, atol=None, names=("a", "b")):
    if not almost_equal_ignore_nan(a, b, rtol, atol):
        raise AssertionError(
            f"{names[0]} != {names[1]} (ignoring NaN) within "
            f"rtol={rtol} atol={atol}")


def assert_exception(f, exception_type, *args, **kwargs):
    """reference test_utils.py assert_exception(f, exc, ...) — note the
    REVERSED argument order vs assert_raises(exc, f, ...)."""
    return assert_raises(exception_type, f, *args, **kwargs)


def _bind_with_location(sym, location, aux_states, ctx, grad_req="null"):
    from . import nd as _nd
    from .context import cpu as _cpu
    ctx = ctx or default_context()
    names = sym.list_arguments()
    if isinstance(location, dict):
        args = {k: _nd.array(v, ctx=ctx) for k, v in location.items()}
    else:
        args = {n: _nd.array(v, ctx=ctx) for n, v in zip(names, location)}
    aux = None
    if aux_states is not None:
        aux_names = sym.list_auxiliary_states()
        if isinstance(aux_states, dict):
            aux = {k: _nd.array(v, ctx=ctx) for k, v in aux_states.items()}
        else:
            aux = {n: _nd.array(v, ctx=ctx)
                   for n, v in zip(aux_names, aux_states)}
    return sym.bind(ctx, args, args_grad=None if grad_req == "null" else {
        n: _nd.zeros(a.shape, ctx=ctx, dtype=a.dtype)
        for n, a in args.items()}, grad_req=grad_req, aux_states=aux), args


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None, equal_nan=False):
    """Bind + forward and compare each output against `expected`
    (reference test_utils.py:1124)."""
    exe, _ = _bind_with_location(sym, location, aux_states, ctx)
    outs = exe.forward(is_train=False)
    assert len(outs) == len(expected), \
        f"{len(outs)} outputs vs {len(expected)} expected"
    for i, (o, e) in enumerate(zip(outs, expected)):
        if equal_nan:
            assert_almost_equal_ignore_nan(o, e, rtol, atol,
                                           names=(f"output[{i}]", "expected"))
        else:
            assert_almost_equal(o, e, rtol, atol)
    return outs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None, equal_nan=False):
    """Bind + forward + backward and compare input gradients (reference
    test_utils.py:1194). `expected` maps argument name -> gradient (or is
    a positional list)."""
    from . import nd as _nd
    exe, args = _bind_with_location(sym, location, aux_states, ctx,
                                    grad_req=grad_req)
    exe.forward(is_train=True)
    ogs = [_nd.array(g) for g in out_grads] if out_grads is not None else None
    exe.backward(out_grads=ogs)
    grads = dict(zip(sym.list_arguments(), exe.grad_arrays))
    if not isinstance(expected, dict):
        expected = dict(zip(sym.list_arguments(), expected))
    for name, e in expected.items():
        if e is None:
            continue
        g = grads[name]
        if equal_nan:
            assert_almost_equal_ignore_nan(g, e, rtol, atol,
                                           names=(f"grad[{name}]", "expected"))
        else:
            assert_almost_equal(g, e, rtol, atol)
    return grads


def check_speed(sym, location=None, ctx=None, N=20, grad_req="write",
                typ="whole", **kwargs):
    """Average seconds per forward(+backward) run (reference
    test_utils.py:1340). typ='whole' times fwd+bwd, 'forward' only fwd."""
    import time as _time
    from . import nd as _nd
    ctx = ctx or default_context()
    if location is None:
        arg_shapes, _, _ = sym.infer_shape(**kwargs)
        rng = _np.random.RandomState(0)
        location = {n: rng.normal(0, 1, s).astype("float32")
                    for n, s in zip(sym.list_arguments(), arg_shapes)}
    exe, _ = _bind_with_location(
        sym, location, None, ctx,
        grad_req=grad_req if typ == "whole" else "null")

    def once():
        outs = exe.forward(is_train=(typ == "whole"))
        if typ == "whole":
            exe.backward()
            _ = [g.asnumpy() for g in exe.grad_arrays if g is not None]
        else:
            _ = [o.asnumpy() for o in outs]

    once()  # warmup/compile
    t0 = _time.perf_counter()
    for _ in range(N):
        once()
    return (_time.perf_counter() - t0) / N


def rand_sparse_ndarray(shape, stype, density=0.5, dtype="float32",
                        rng=None):
    """Random sparse array + its dense numpy value (reference
    test_utils.py rand_sparse_ndarray, simplified to the data-generation
    contract the tests use)."""
    from . import nd as _nd
    rng = rng or _np.random.RandomState(0)
    x = rng.uniform(-1, 1, shape).astype(dtype)
    x[rng.uniform(0, 1, shape) > density] = 0
    return _nd.cast_storage(_nd.array(x), stype), x
