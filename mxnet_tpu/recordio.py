"""RecordIO reader/writer (reference python/mxnet/recordio.py + dmlc recordio).

Binary-compatible with the reference format:
  each record = [kMagic:u32][lrec:u32][data...pad to 4B]
  kMagic = 0xced7230a; upper 3 bits of lrec encode continue-flag for
  multi-part records; IRHeader packs (flag:u32, label:f32, id:u64, id2:u64).

The C++ runtime lives in src/native/recordio.cc (threaded prefetch reader,
index scanner, writer) and is bound in mxnet_tpu.native; NativeRecordReader/
NativeRecordWriter below re-export it. This pure-python class remains the
portable fallback and the random-access (tell/seek) surface.
"""
from __future__ import annotations

import ctypes
import numbers
import os
import struct
from collections import namedtuple

import numpy as _np

from .base import MXNetError

_kMagic = 0xced7230a

IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential reader/writer (reference recordio.py MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"invalid flag {self.flag}")
        self.is_open = True

    def close(self):
        if self.is_open:
            self.handle.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["handle"] = None
        d["is_open"] = False
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()
        if not self.writable:
            self.reset()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.handle.tell()

    def seek(self, pos):
        self.handle.seek(pos)

    def write(self, buf: bytes):
        assert self.writable
        lrec = len(buf)
        if lrec >= (1 << 29):
            # would leak into the header's continue-flag bits; the read path
            # masks with (1<<29)-1 and would silently mis-frame the stream
            raise MXNetError("record too large (>= 512 MB)")
        self.handle.write(struct.pack("<II", _kMagic, lrec))
        self.handle.write(buf)
        pad = (4 - lrec % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        head = self.handle.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _kMagic:
            raise MXNetError("invalid record magic")
        length = lrec & ((1 << 29) - 1)
        buf = self.handle.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)
        return buf


class MXIndexedRecordIO(MXRecordIO):
    """Indexed random-access reader (reference MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)
        elif not self.writable:
            # No .idx file: rebuild by POSITION via the native C++ scanner
            # (keys become 0..n-1 — original non-contiguous .lst keys cannot
            # be recovered without the .idx). Cached so per-epoch reset()
            # doesn't rescan the file.
            cached = getattr(self, "_native_index_cache", None)
            if cached is not None and cached[0] == self.uri:
                offs = cached[1]
            else:
                offs = None
                try:
                    from .native import available, build_index
                    if available():
                        offs, _ = build_index(self.uri)
                        self._native_index_cache = (self.uri, offs)
                except Exception:
                    offs = None
            if offs is not None:
                for i, off in enumerate(offs):
                    key = self.key_type(i)
                    self.idx[key] = int(off)
                    self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.writable:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a label header + payload (reference recordio.py pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        hdr = header._replace(flag=0)
        payload = struct.pack(_IR_FORMAT, *hdr)
    else:
        label = _np.asarray(header.label, dtype=_np.float32)
        hdr = header._replace(flag=label.size, label=0)
        payload = struct.pack(_IR_FORMAT, *hdr) + label.tobytes()
    return payload + s


def unpack(s: bytes):
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = _np.frombuffer(s[:header.flag * 4], dtype=_np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=1):
    header, s = unpack(s)
    arr = _np.frombuffer(s, dtype=_np.uint8)
    try:
        import cv2
        img = cv2.imdecode(arr, iscolor)
    except ImportError:
        raise MXNetError("image decode requires cv2 or pre-decoded .npy records")
    return header, img


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    try:
        import cv2
        ok, buf = cv2.imencode(img_fmt, img,
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        assert ok
        return pack(header, buf.tobytes())
    except ImportError:
        raise MXNetError("pack_img requires cv2")


# Native C++ fast path (src/native/recordio.cc via ctypes)
try:
    from .native import (NativeRecordReader, NativeRecordWriter,  # noqa: F401
                         available as native_available,
                         build_index as native_build_index)
except Exception:  # pragma: no cover
    def native_available():
        return False
