"""Fault schedules: WHEN an armed injection point fires.

A schedule is a small deterministic state machine driven by the per-point
attempt counter the plane maintains (1-based, incremented on every
``faults.check(point)`` call). Determinism is the whole design: the same
schedule spec against the same code path fires on the same attempts in
every run, so a chaos test is an exact replay — never a flake.

Spec grammar (one schedule)::

    every_nth:N          fire on attempts N, 2N, 3N, ...
    first_k:K            fire on attempts 1..K, then never again
    p:P[:seedS]          seeded Bernoulli(P) per attempt (own RNG stream,
                         default seed 0 — still fully deterministic)

and ``parse_spec`` reads the full ``MXNET_TPU_FAULTS`` form::

    point=schedule[;point=schedule...]
    e.g.  elastic.write_shard=first_k:1;serving.dispatch=every_nth:3
"""
from __future__ import annotations

import random
from typing import Dict, List, Tuple

from ..base import MXNetError

__all__ = ["Schedule", "EveryNth", "FirstK", "SeededProbability",
           "parse_schedule", "parse_spec"]


class Schedule:
    """Base: ``fires(attempt)`` decides whether attempt #n (1-based)
    injects. Instances may hold state (RNG stream); the plane serializes
    calls under its lock, so schedules need no locking of their own."""

    def fires(self, attempt: int) -> bool:
        raise NotImplementedError

    def spec(self) -> str:
        raise NotImplementedError

    def __repr__(self):
        return f"<{type(self).__name__} {self.spec()}>"


class EveryNth(Schedule):
    """Fire on every Nth attempt (N=1 means always)."""

    def __init__(self, n: int):
        if int(n) < 1:
            raise MXNetError(f"every_nth needs n >= 1, got {n}")
        self.n = int(n)

    def fires(self, attempt: int) -> bool:
        return attempt % self.n == 0

    def spec(self) -> str:
        return f"every_nth:{self.n}"


class FirstK(Schedule):
    """Fire on the first K attempts only — the canonical 'transient fault
    that a bounded retry must absorb' schedule."""

    def __init__(self, k: int):
        if int(k) < 0:
            raise MXNetError(f"first_k needs k >= 0, got {k}")
        self.k = int(k)

    def fires(self, attempt: int) -> bool:
        return attempt <= self.k

    def spec(self) -> str:
        return f"first_k:{self.k}"


class SeededProbability(Schedule):
    """Bernoulli(p) per attempt from a private seeded stream: the same
    seed replays the identical fire/no-fire sequence."""

    def __init__(self, p: float, seed: int = 0):
        p = float(p)
        if not 0.0 <= p <= 1.0:
            raise MXNetError(f"probability schedule needs 0 <= p <= 1, "
                             f"got {p}")
        self.p = p
        self.seed = int(seed)
        self._rng = random.Random(self.seed)

    def fires(self, attempt: int) -> bool:
        return self._rng.random() < self.p

    def spec(self) -> str:
        return f"p:{self.p}:seed{self.seed}"


def parse_schedule(text: str) -> Schedule:
    """``every_nth:3`` / ``first_k:2`` / ``p:0.1[:seed7]`` -> Schedule."""
    parts = [p.strip() for p in str(text).strip().split(":")]
    kind = parts[0]
    try:
        if kind == "every_nth" and len(parts) == 2:
            return EveryNth(int(parts[1]))
        if kind == "first_k" and len(parts) == 2:
            return FirstK(int(parts[1]))
        if kind == "p" and len(parts) in (2, 3):
            seed = 0
            if len(parts) == 3:
                s = parts[2]
                seed = int(s[len("seed"):] if s.startswith("seed") else s)
            return SeededProbability(float(parts[1]), seed)
    except (ValueError, IndexError):
        pass
    raise MXNetError(
        f"unparseable fault schedule {text!r}; expected every_nth:N, "
        "first_k:K, or p:P[:seedS] (docs/reliability.md)")


def parse_spec(spec: str) -> List[Tuple[str, Schedule]]:
    """Parse the ``MXNET_TPU_FAULTS`` value into (point, schedule) pairs."""
    out: List[Tuple[str, Schedule]] = []
    seen: Dict[str, str] = {}
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise MXNetError(
                f"unparseable fault spec entry {part!r}; expected "
                "point=schedule (docs/reliability.md)")
        point, _, sched = part.partition("=")
        point = point.strip()
        if not point:
            raise MXNetError(f"empty fault point in spec entry {part!r}")
        if point in seen:
            raise MXNetError(
                f"fault point {point!r} appears twice in spec "
                f"({seen[point]!r} then {sched.strip()!r})")
        seen[point] = sched.strip()
        out.append((point, parse_schedule(sched)))
    return out
