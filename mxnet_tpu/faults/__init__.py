"""Deterministic fault-injection plane (chaos layer) for mxnet_tpu.

PR 11 made training *resumable*; this package makes the failure paths
*tested*. Named injection points are threaded through the stack (elastic
snapshot IO, the DeviceFeed producer, the serving dispatcher and HTTP
front door — ``points()`` is the live catalog) and fire ``FaultInjected``
according to deterministic schedules, so every recovery path — IO retry,
commit fencing, load shedding, producer restart — is exercised by exact
replayable chaos tests instead of hand monkeypatches.

Design rules (telemetry precedent, PR 2):

  - **Off by default, one-flag free path.** Instrumented sites guard with
    ``if _faults._ACTIVE: _faults.check("point")`` — a module-attribute
    load and a branch when disarmed, nothing else. ``BENCH_SCENARIO=chaos``
    holds this under 1% on the snapshot hot path.
  - **Deterministic.** Schedules are pure functions of the per-point
    attempt counter (plus a private seeded RNG stream for probability
    schedules); the same spec replays the same fault sequence.
  - **Process-wide.** Armed via ``MXNET_TPU_FAULTS=<spec>`` at import or
    ``faults.inject(point, schedule)`` / the ``faults.injected(...)``
    context manager in tests.

The plane also hosts :func:`io_retry` — bounded exponential-backoff+jitter
retry for transient IO (``OSError`` and injected faults), the hardening
primitive the elastic writer/reader paths are wrapped in. See
docs/reliability.md for the catalog, grammar, and tuning guidance.
"""
from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional, Union

from ..base import MXNetError, env
from .schedule import (Schedule, EveryNth, FirstK, SeededProbability,
                       parse_schedule, parse_spec)

__all__ = ["FaultInjected", "Schedule", "EveryNth", "FirstK",
           "SeededProbability", "parse_schedule", "parse_spec",
           "declare_point", "points", "inject", "injected", "clear",
           "armed", "check", "attempts", "fired", "install_from_env",
           "io_retry"]

env.declare("MXNET_TPU_FAULTS", "", str,
            "Arm the fault-injection plane at import: "
            "point=schedule[;point=schedule...] where schedule is "
            "every_nth:N, first_k:K, or p:P[:seedS] "
            "(docs/reliability.md); empty = disarmed")
env.declare("MXNET_TPU_IO_RETRIES", 3, int,
            "Bounded retries for transient elastic/serving IO failures "
            "(OSError + injected faults) around each io_retry-wrapped "
            "operation; 0 disables retry (first failure surfaces)")
env.declare("MXNET_TPU_IO_BACKOFF", 0.05, float,
            "Base delay (seconds) for io_retry exponential backoff; the "
            "k-th retry sleeps uniform(0, min(cap, base*2^k)) — full "
            "jitter, so racing writers decorrelate")
env.declare("MXNET_TPU_IO_BACKOFF_MAX", 1.0, float,
            "Backoff delay cap (seconds) for io_retry")


class FaultInjected(MXNetError):
    """Raised by an armed injection point. Deliberately a transient-style
    error: retry/restart layers treat it exactly like an ``OSError`` from
    the real world, which is what makes injected chaos prove the same
    recovery path production faults take."""

    def __init__(self, point: str, attempt: int):
        super().__init__(
            f"injected fault at {point!r} (attempt {attempt})")
        self.point = point
        self.attempt = attempt


# _ACTIVE is THE disabled-path guard: call sites check this module
# attribute before calling check(), so a disarmed plane costs one
# attribute load + branch (same idiom as telemetry._ENABLED).
_ACTIVE = False

_LOCK = threading.Lock()
_POINTS: Dict[str, str] = {}       # name -> doc (static catalog + ad hoc)
_SCHEDULES: Dict[str, Schedule] = {}
_ATTEMPTS: Dict[str, int] = {}     # 1-based check() count per point
_FIRED: Dict[str, int] = {}


def declare_point(name: str, doc: str = ""):
    """Register an injection point in the catalog (idempotent). Sites may
    check undeclared points too — they are added on first ``inject`` —
    but the canonical set below is what docs and tests enumerate."""
    with _LOCK:
        _POINTS.setdefault(name, doc)


for _name, _doc in (
    ("elastic.write_shard", "shard .npz/.json payload+index write "
                            "(elastic/manifest.py write_shard)"),
    ("elastic.commit", "manifest merge + atomic rename "
                       "(elastic/manifest.py commit)"),
    ("elastic.read", "snapshot manifest/chunk reads "
                     "(elastic/manifest.py load + SnapshotReader)"),
    ("feed.produce", "DeviceFeed producer next() on the wrapped source "
                     "(engine/async_feed.py)"),
    ("serving.load", "model artifact load at registration "
                     "(serving/registry.py)"),
    ("serving.dispatch", "continuous-batcher batch assemble/forward "
                         "(serving/batcher.py)"),
    ("serving.http", "HTTP front-door request handling "
                     "(serving/server.py)"),
    ("elastic.heartbeat", "coordinator membership heartbeat write "
                          "(elastic/coordinator.py heartbeat)"),
    ("elastic.barrier", "coordinator generation/stop barrier IO "
                        "(elastic/coordinator.py generation epoch, "
                        "stop intent + acks)"),
    ("elastic.marker", "per-host ready-marker write in the two-phase "
                       "cross-host commit (elastic/coordinator.py "
                       "write_marker)"),
):
    declare_point(_name, _doc)


def points() -> Dict[str, str]:
    """The injection-point catalog: name -> where it is threaded."""
    with _LOCK:
        return dict(_POINTS)


def check(point: str):
    """Count one attempt at ``point`` and raise :class:`FaultInjected` if
    the armed schedule says this attempt fires. Call sites keep this off
    the free path behind the ``_ACTIVE`` module flag."""
    with _LOCK:
        n = _ATTEMPTS.get(point, 0) + 1
        _ATTEMPTS[point] = n
        sched = _SCHEDULES.get(point)
        fire = sched is not None and sched.fires(n)
        if fire:
            _FIRED[point] = _FIRED.get(point, 0) + 1
    if fire:
        from .. import telemetry as _telem
        if _telem._ENABLED:
            _telem.record_fault_injected(point)
        from ..telemetry import tracing as _tracing
        if _tracing._ENABLED:
            # every injected fault is a flight-recorder event: the crash
            # dump shows exactly which chaos fired before the failure
            _tracing.event("mx.fault", point=point, attempt=n)
        raise FaultInjected(point, n)


def inject(point: str, schedule: Union[Schedule, str]):
    """Arm ``point`` with a schedule (instance or spec string)."""
    global _ACTIVE
    if isinstance(schedule, str):
        schedule = parse_schedule(schedule)
    if not isinstance(schedule, Schedule):
        raise MXNetError(f"inject needs a Schedule or spec string, "
                         f"got {type(schedule).__name__}")
    with _LOCK:
        _POINTS.setdefault(point, "")
        _SCHEDULES[point] = schedule
        _ACTIVE = True


def clear(point: Optional[str] = None):
    """Disarm one point, or the whole plane (and reset counters) when
    called without arguments."""
    global _ACTIVE
    with _LOCK:
        if point is None:
            _SCHEDULES.clear()
            _ATTEMPTS.clear()
            _FIRED.clear()
        else:
            _SCHEDULES.pop(point, None)
        _ACTIVE = bool(_SCHEDULES)


@contextmanager
def injected(point: str, schedule: Union[Schedule, str]):
    """Test helper: arm ``point`` for the block, disarm on exit."""
    inject(point, schedule)
    try:
        yield
    finally:
        clear(point)


def armed() -> Dict[str, str]:
    """Currently armed points -> schedule spec."""
    with _LOCK:
        return {p: s.spec() for p, s in _SCHEDULES.items()}


def attempts(point: str) -> int:
    with _LOCK:
        return _ATTEMPTS.get(point, 0)


def fired(point: str) -> int:
    with _LOCK:
        return _FIRED.get(point, 0)


def install_from_env():
    """Arm the plane from ``MXNET_TPU_FAULTS`` (called at import; a bad
    spec fails loudly here rather than silently running chaos-free)."""
    spec = str(env.get("MXNET_TPU_FAULTS") or "").strip()
    if not spec:
        return
    for point, sched in parse_spec(spec):
        inject(point, sched)


# ---------------------------------------------------------------------------
# Bounded retry: the hardening primitive the injector targets
# ---------------------------------------------------------------------------

def io_retry(point: str, fn, *args, retries: Optional[int] = None,
             backoff: Optional[float] = None, **kwargs):
    """Run ``fn(*args, **kwargs)`` with the named fault point checked on
    every attempt and transient failures (``OSError`` and injected
    faults) retried with exponential backoff + full jitter.

    Retry budget is ``MXNET_TPU_IO_RETRIES`` (or ``retries``); the k-th
    retry sleeps ``uniform(0, min(cap, base * 2**k))`` with base
    ``MXNET_TPU_IO_BACKOFF`` and cap ``MXNET_TPU_IO_BACKOFF_MAX`` — full
    jitter so concurrent writers hitting the same contended filesystem
    decorrelate. Every retry books ``mx_io_retries_total{point}``.
    Non-transient errors (``MXNetError`` subclasses other than
    :class:`FaultInjected` — e.g. a lost commit fence) are NEVER retried:
    retrying a fenced-out writer is exactly the interleaving the lease
    exists to prevent."""
    budget = int(env.get("MXNET_TPU_IO_RETRIES")) if retries is None \
        else int(retries)
    base = float(env.get("MXNET_TPU_IO_BACKOFF")) if backoff is None \
        else float(backoff)
    cap = float(env.get("MXNET_TPU_IO_BACKOFF_MAX"))
    from ..telemetry import tracing as _tracing
    attempt = 0
    while True:
        t0 = time.perf_counter() if _tracing._ENABLED else 0.0
        try:
            if _ACTIVE:
                check(point)
            out = fn(*args, **kwargs)
            if _tracing._ENABLED:
                _tracing.record_span("mx.io." + point, t0,
                                     time.perf_counter(),
                                     attempt=attempt, status="ok")
            return out
        except FaultInjected:
            if _tracing._ENABLED:
                _tracing.record_span("mx.io." + point, t0,
                                     time.perf_counter(),
                                     attempt=attempt, status="fault")
            if attempt >= budget:
                raise
        except MXNetError:
            raise               # permanent by design (fence, validation)
        except OSError as e:
            if _tracing._ENABLED:
                _tracing.record_span("mx.io." + point, t0,
                                     time.perf_counter(),
                                     attempt=attempt, status="error",
                                     error=type(e).__name__)
            if attempt >= budget:
                raise
        attempt += 1
        from .. import telemetry as _telem
        if _telem._ENABLED:
            _telem.record_io_retry(point)
        if _tracing._ENABLED:
            _tracing.event("mx.io_retry", point=point, attempt=attempt)
        delay = min(cap, base * (2 ** (attempt - 1)))
        if delay > 0:
            time.sleep(random.uniform(0, delay))


install_from_env()
