"""NDArray / parameter serialization (reference src/ndarray/ndarray.cc
Save/Load dmlc stream format; python mx.nd.save/load).

Format: numpy .npz container with a manifest — portable, mmap-friendly,
and safe (no pickle). Keys keep MXNet conventions (`arg:`/`aux:` prefixes
are preserved verbatim so Gluon save/load round-trips).
"""
from __future__ import annotations

import json
import os
import zipfile
from typing import Dict, List, Union

import numpy as _np

from .base import MXNetError

_MAGIC = "mxnet_tpu_ndarray_v1"


def _to_numpy(arr):
    a = arr.asnumpy() if hasattr(arr, "asnumpy") else _np.asarray(arr)
    if a.dtype.name == "bfloat16":  # ml_dtypes bfloat16 -> store as f32 + tag
        return a.astype(_np.float32), "bfloat16"
    return a, str(a.dtype)


def save_ndarrays(fname: str, data) -> None:
    from .ndarray import NDArray
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        keys = [f"__list__{i}" for i in range(len(data))]
        vals = list(data)
    elif isinstance(data, dict):
        keys = list(data.keys())
        vals = list(data.values())
    else:
        raise MXNetError("save: expected NDArray, list, or dict")
    arrays = {}
    manifest = {"magic": _MAGIC, "entries": []}
    for i, (k, v) in enumerate(zip(keys, vals)):
        a, dt = _to_numpy(v)
        arrays[f"a{i}"] = a
        manifest["entries"].append({"key": k, "dtype": dt, "slot": f"a{i}"})
    tmp = fname + ".tmp"
    with open(tmp, "wb") as f:
        _np.savez(f, __manifest__=_np.frombuffer(
            json.dumps(manifest).encode(), dtype=_np.uint8), **arrays)
    os.replace(tmp, fname)


def load_ndarrays(fname: str):
    from .ndarray import array
    import jax.numpy as jnp
    with _np.load(fname, allow_pickle=False) as z:
        manifest = json.loads(bytes(z["__manifest__"]).decode())
        if manifest.get("magic") != _MAGIC:
            raise MXNetError(f"{fname}: not a mxnet_tpu ndarray file")
        out = {}
        is_list = True
        for e in manifest["entries"]:
            a = z[e["slot"]]
            if e["dtype"] == "bfloat16":
                nd = array(a, dtype=jnp.bfloat16)
            else:
                nd = array(a, dtype=a.dtype)
            out[e["key"]] = nd
            if not e["key"].startswith("__list__"):
                is_list = False
    if is_list and out:
        return [out[f"__list__{i}"] for i in range(len(out))]
    return out
