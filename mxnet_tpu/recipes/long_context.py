"""Long-context training recipe (docs/large_models.md).

A causal LM whose attention path scales past 32k tokens:

  - single device / pure dp: every attention call goes through the
    registered flash kernel (``ops/pallas/flash_attention.py`` on TPU, the
    ``blockwise_attention`` lax.scan fallback elsewhere) — O(T) activation
    memory, so MXNET_TPU_LONG_CONTEXT_SEQ=32768 runs on a CPU host;
  - under ``LongContextTrainer`` the mesh gains an 'sp' axis: the token
    dimension is sharded ``P('dp','sp')`` and the SAME model cells switch
    to ``ring_attention`` (kv shards rotate over ppermute, comm overlaps
    compute) via the ``sequence_axis`` trace context — the long-context
    analog of ``parallel.moe.expert_axis``;
  - the parity oracle is the identical architecture with the dense O(T^2)
    softmax path (``dense_attention=True``); ring and flash/blockwise
    outputs must match it (tests/test_recipes.py).

Sequence chunking: ``TokenWindows`` slices a corpus into shifted
(next-token) windows and rides ``DeviceFeed.for_trainer`` so batches land
pre-sharded on the dp x sp mesh.
"""
from __future__ import annotations

import contextlib
from typing import List

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from ..base import MXNetError, env
from ..ndarray import NDArray
from ..engine import async_feed as _feed
from .. import telemetry as _telem
from ..gluon.block import HybridBlock
from ..gluon import nn
from ..ops.attention import ring_attention
from ..parallel import zero as _zero
from ..parallel.data_parallel import DataParallelTrainer, _make_apply_fn
from ..parallel.mesh import require_axis, P
from ..parallel.step_program import StepProgram
from .moe import token_cross_entropy

__all__ = ["LongContextLM", "LongContextTrainer", "TokenWindows",
           "sequence_axis", "current_sequence_axis", "default_seq_len",
           "make_model", "make_oracle", "make_trainer", "make_feed"]

env.declare("MXNET_TPU_LONG_CONTEXT_SEQ", 32768, int,
            "Default sequence length of the long-context recipe "
            "(recipes/long_context.py); the model builder and bench lane "
            "read it, so one env var scales the whole workload.")


def default_seq_len() -> int:
    return int(env.get("MXNET_TPU_LONG_CONTEXT_SEQ"))


# -- trace context: which mesh axis shards the sequence ---------------------

class _SeqCtx:
    __slots__ = ("axis_name",)

    def __init__(self, axis_name):
        self.axis_name = axis_name


_SEQ_STACK: List[_SeqCtx] = []


@contextlib.contextmanager
def sequence_axis(axis_name: str):
    """Trace context: inside it, LongContextLM's attention runs
    ``ring_attention`` over `axis_name` (the caller must be under a
    shard_map mapping that axis, with (B, T/sp, ...) local activations)."""
    _SEQ_STACK.append(_SeqCtx(axis_name))
    try:
        yield
    finally:
        _SEQ_STACK.pop()


def current_sequence_axis():
    return _SEQ_STACK[-1] if _SEQ_STACK else None


# -- model ------------------------------------------------------------------

class RingSelfAttention(HybridBlock):
    """Causal self-attention with three runtime paths over one parameter
    set: ring (under ``sequence_axis``), flash/blockwise (default), dense
    O(T^2) softmax (``dense_attention=True`` — the parity oracle)."""

    def __init__(self, units, num_heads, dense_attention=False, **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        self._units = units
        self._heads = num_heads
        self._dense = dense_attention
        self.qkv = nn.Dense(3 * units, flatten=False, in_units=units)
        self.proj = nn.Dense(units, flatten=False, in_units=units)

    def hybrid_forward(self, F, x):
        if not isinstance(x, NDArray):
            raise MXNetError("RingSelfAttention has no symbolic form; "
                             "export the dense-oracle model instead")
        H = self._heads
        d = self._units // H
        qkv = self.qkv(x)._data                  # (B, T, 3C)
        B, T, _ = qkv.shape
        q, k, v = (jnp.transpose(a.reshape(B, T, H, d), (0, 2, 1, 3))
                   for a in jnp.split(qkv, 3, axis=-1))
        ctx = current_sequence_axis()
        if ctx is not None:
            out = ring_attention(q, k, v, ctx.axis_name, causal=True)
        elif self._dense:
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                           preferred_element_type=jnp.float32) / (d ** 0.5)
            mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
            s = jnp.where(mask[None, None], s, -1e30)
            out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1),
                             v.astype(jnp.float32)).astype(q.dtype)
        else:
            # Pallas flash on TPU, blockwise lax.scan fallback elsewhere —
            # O(T) activation memory either way (the >=32k lane's enabler)
            from ..ops.attention import flash_attention_op
            out = flash_attention_op(q, k, v, causal=True)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(B, T, H * d)
        return self.proj(NDArray(out))


class _LCCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dense_attention=False,
                 **kwargs):
        super().__init__(**kwargs)
        from ..models.bert import PositionwiseFFN
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.attn = RingSelfAttention(units, num_heads,
                                      dense_attention=dense_attention)
        self.ln2 = nn.LayerNorm(in_channels=units)
        self.ffn = PositionwiseFFN(units, hidden_size)

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.ln1(x))
        x = x + self.ffn(self.ln2(x))
        return x


class LongContextLM(HybridBlock):
    """Pre-LN causal LM over ring/flash attention. Under ``sequence_axis``
    each device holds a T/sp token slice; position embeddings offset by
    ``axis_index(sp) * T_local`` so every shard sees its GLOBAL positions."""

    def __init__(self, vocab_size, num_layers=2, units=64, hidden_size=128,
                 num_heads=2, max_length=None, dense_attention=False,
                 **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = int(max_length if max_length is not None
                               else default_seq_len())
        self.word_embed = nn.Embedding(vocab_size, units)
        self.pos_embed = nn.Embedding(self._max_length, units)
        self.embed_ln = nn.LayerNorm(in_channels=units)
        self.cells = nn.HybridSequential()
        for _ in range(num_layers):
            self.cells.add(_LCCell(units, hidden_size, num_heads,
                                   dense_attention=dense_attention))
        self.ln = nn.LayerNorm(in_channels=units)
        self.decoder = nn.Dense(vocab_size, flatten=False, in_units=units)

    def hybrid_forward(self, F, token_ids):
        if not isinstance(token_ids, NDArray):
            raise MXNetError("LongContextLM has no symbolic form")
        Tl = token_ids.shape[1]
        pos = jnp.arange(Tl, dtype=jnp.int32)
        ctx = current_sequence_axis()
        if ctx is not None:
            pos = pos + lax.axis_index(ctx.axis_name) * Tl
        x = self.word_embed(token_ids) \
            + self.pos_embed(NDArray(pos)).expand_dims(axis=0)
        x = self.embed_ln(x)
        x = self.cells(x)
        return self.decoder(self.ln(x))

    def pipeline_split(self):
        """(embed, cells, head) for parallel.PipelineTrainer. The wrappers
        re-register this model's own child blocks, so parameters are
        shared and sync() writes straight back into this model."""
        cells = [self.cells[i] for i in range(len(self.cells))]
        return _LCEmbedStage(self), cells, _LCHeadStage(self)


class _LCEmbedStage(HybridBlock):
    """Pipeline stage 0 body: LongContextLM's embedding section (keeps
    the sequence-axis position offset so ring runs still see GLOBAL
    positions)."""

    def __init__(self, lm, **kwargs):
        super().__init__(**kwargs)
        self.word_embed = lm.word_embed
        self.pos_embed = lm.pos_embed
        self.embed_ln = lm.embed_ln

    def hybrid_forward(self, F, token_ids):
        if not isinstance(token_ids, NDArray):
            raise MXNetError("LongContextLM has no symbolic form")
        Tl = token_ids.shape[1]
        pos = jnp.arange(Tl, dtype=jnp.int32)
        ctx = current_sequence_axis()
        if ctx is not None:
            pos = pos + lax.axis_index(ctx.axis_name) * Tl
        x = self.word_embed(token_ids) \
            + self.pos_embed(NDArray(pos)).expand_dims(axis=0)
        return self.embed_ln(x)


class _LCHeadStage(HybridBlock):
    """Pipeline last-stage tail: final LN + LM decoder."""

    def __init__(self, lm, **kwargs):
        super().__init__(**kwargs)
        self.ln = lm.ln
        self.decoder = lm.decoder

    def hybrid_forward(self, F, x):
        return self.decoder(self.ln(x))


# -- sequence chunking through DeviceFeed -----------------------------------

class TokenWindows:
    """Re-iterable (x, y) next-token windows over a flat token stream —
    the ``DeviceFeed`` source for the recipe. Each epoch yields
    ``len(tokens) // (batch_size * seq_len + 1)``-ish batches of shape
    (batch_size, seq_len); y is x shifted by one."""

    def __init__(self, tokens, batch_size, seq_len):
        self._tokens = _np.asarray(tokens, dtype=_np.int32)
        self.batch_size = int(batch_size)
        self.seq_len = int(seq_len)
        span = self.batch_size * self.seq_len
        self.n_batches = max((len(self._tokens) - 1) // span, 0)
        if not self.n_batches:
            raise MXNetError(
                f"token stream too short: {len(self._tokens)} tokens < one "
                f"({batch_size} x {seq_len}) window")

    def __len__(self):
        return self.n_batches

    def __iter__(self):
        span = self.batch_size * self.seq_len
        for b in range(self.n_batches):
            lo = b * span
            x = self._tokens[lo:lo + span]
            y = self._tokens[lo + 1:lo + span + 1]
            yield (x.reshape(self.batch_size, self.seq_len),
                   y.reshape(self.batch_size, self.seq_len))


def make_feed(source, trainer, depth=None):
    """Batches land pre-placed with the trainer's P(dp, sp) input spec."""
    return _feed.DeviceFeed.for_trainer(source, trainer, depth=depth,
                                        name="long_context")


# -- the dp x sp fused trainer ----------------------------------------------

class LongContextTrainer(DataParallelTrainer):
    """Fused step over a {'dp': d, 'sp': s} mesh: batch over dp, SEQUENCE
    over sp (``data_spec=P('dp','sp')``), ring attention inside the cells,
    all parameters replicated with ZeRO-over-dp optimizer state. The
    gradient normalizer folds the sp sum into the dp reduce-scatter —
    psum over sp, reduce-scatter over dp, /(d*s) — so the update equals
    the single-device full-sequence gradient."""

    def __init__(self, net, loss, optimizer="adam", optimizer_params=None,
                 mesh=None, dp_axis="dp", sp_axis="sp", comm_dtype=None,
                 bucket_bytes=None):
        from ..parallel.mesh import current_mesh
        mesh = mesh if mesh is not None else current_mesh()
        require_axis(mesh, dp_axis, "LongContextTrainer data parallelism")
        self._sp_axis = sp_axis
        self._sp_degree = require_axis(mesh, sp_axis,
                                       "LongContextTrainer sequence "
                                       "parallelism")
        super().__init__(net, loss, optimizer=optimizer,
                         optimizer_params=optimizer_params, mesh=mesh,
                         batch_axis_name=dp_axis, dtype="float32",
                         data_spec=P(dp_axis, sp_axis), zero_update=True,
                         bucket_bytes=bucket_bytes, comm_dtype=comm_dtype,
                         overlap_grads=False)
        self._step_key_base = self._step_key_base + (
            ("long_context", sp_axis, self._sp_degree),)
        self._program = StepProgram(
            f"lc.step[{type(net).__name__}]", self._step_key_base)

    def _validate_zero(self, compression):
        """Relax the parent's data-spec check to P(dp, sp); everything else
        (replicated params, dense grads, elementwise optimizer) holds."""
        if compression:
            raise MXNetError("LongContextTrainer does not support 2-bit "
                             "gradient compression")
        bad = [p.name for p, s in zip(self._plist, self._param_shardings)
               if any(ax is not None for ax in s.spec)]
        if bad:
            raise MXNetError("LongContextTrainer requires replicated "
                             f"parameters; offending {bad[:3]}")
        sparse = [p.name for p, lz in zip(self._plist, self._lazy) if lz]
        if sparse:
            raise MXNetError("LongContextTrainer is incompatible with "
                             f"row_sparse parameters ({sparse[:3]})")
        from ..optimizer.optimizer import LAMB, LARS
        if isinstance(self.optimizer, (LAMB, LARS)):
            raise MXNetError(
                f"{type(self.optimizer).__name__} trust ratios do not "
                "decompose over flat bucket shards")

    def _build_step_zero(self):
        aux_order = []
        apply_fn = _make_apply_fn(self.net, self._plist, train=True,
                                  aux_order_out=aux_order)
        plist = self._plist
        update_fn = self._update_fn
        loss_raw = self._loss_raw
        wds = self._wds
        trainable = self._trainable
        mesh = self.mesh
        dp_ax = self.batch_axis
        sp_ax = self._sp_axis
        ndp = self._dp_degree
        nsp = self._sp_degree
        buckets = self._zero_plan
        in_bucket = frozenset(i for b in buckets for i in b.indices)
        comm = self._comm_dtype

        def body(params, opt_state, key, x, y, lr, t, loss_scale):
            bucket_carry, extra_state = opt_state
            dpos = lax.axis_index(dp_ax)
            spos = lax.axis_index(sp_ax)
            kk = jax.random.wrap_key_data(key.astype(jnp.uint32),
                                          impl="threefry2x32")
            key_local = jax.random.key_data(
                jax.random.fold_in(kk, dpos * nsp + spos))

            def lossf(ps):
                with sequence_axis(sp_ax):
                    out, aux = apply_fn(key_local, ps, x)
                pred = out if not isinstance(out, tuple) else out[0]
                # mean over the LOCAL (B/dp, T/sp) token shard; shards are
                # equal-sized, so the cross-axis pmean is the global mean
                return loss_raw(pred, y), aux

            (lossv, aux), grads = jax.value_and_grad(
                lossf, has_aux=True)(params)

            new_params = list(params)
            new_extra = list(extra_state)
            for i, (g, w, s) in enumerate(zip(grads, params, extra_state)):
                if not trainable[i] or i in in_bucket:
                    continue
                gg = lax.pmean(g, (dp_ax, sp_ax))
                w2, s2 = update_fn(gg, w, s, t, lr, jnp.float32(wds[i]))
                new_params[i] = w2.astype(w.dtype)
                new_extra[i] = s2
            new_carry = []
            for b, (wd_vec, st) in zip(buckets, bucket_carry):
                flat_g = lax.psum(_zero.flatten_bucket(b, grads), sp_ax)
                g_shard = _zero.reduce_scatter_bucket(
                    flat_g, dp_ax, ndp, comm) / (ndp * nsp)
                w_shard = _zero.shard_slice(
                    b, _zero.flatten_bucket(b, params), dpos)
                w2, s2 = update_fn(g_shard.astype(w_shard.dtype), w_shard,
                                   st, t, lr, wd_vec)
                full = _zero.all_gather_bucket(w2.astype(w_shard.dtype),
                                               dp_ax)
                for i, arr in _zero.unflatten_bucket(b, full):
                    new_params[i] = arr.astype(params[i].dtype)
                new_carry.append((wd_vec, s2))
            glob_loss = lax.pmean(lossv, (dp_ax, sp_ax))
            aux = jax.tree_util.tree_map(
                lambda v: lax.pmean(v, (dp_ax, sp_ax))
                if jnp.issubdtype(v.dtype, jnp.floating) else v, aux)
            idx_of = {id(p): i for i, p in enumerate(plist)}
            for p, v in zip(aux_order, aux):
                j = idx_of.get(id(p))
                if j is not None and not trainable[j]:
                    new_params[j] = v.astype(new_params[j].dtype)
            return (new_params, (tuple(new_carry), tuple(new_extra)),
                    glob_loss, jnp.isfinite(glob_loss), aux)

        rep = P()
        dp = P(dp_ax)
        param_specs = [s.spec for s in self._param_shardings]
        extra_specs = tuple(rep for _ in self._plist)
        return _zero.shard_map_compat(
            body, mesh=mesh,
            in_specs=(param_specs, (dp, extra_specs), rep, self.data_spec,
                      self.data_spec, rep, rep, rep),
            out_specs=(param_specs, (dp, extra_specs), rep, rep, rep))

    def _record_telemetry(self, sig, examples, steps, flops_key=None):
        if self._sp_degree > 1:
            nbytes, calls = self._ring_step_bytes(sig[0])
            _telem.record_comm("ppermute", nbytes * steps, store="mesh",
                               calls=calls * steps, axis="sp")
        super()._record_telemetry(sig, examples, steps, flops_key=flops_key)

    def _ring_step_bytes(self, x_shape):
        """Per-step ppermute wire bytes: each ring step rotates the local
        k AND v shards (sp-1 hops per attention call), once forward and
        twice in the VJP (rotation replay + cotangent rotation)."""
        B, T = x_shape[0], x_shape[1]  # static python ints (the step sig)
        n_attn = sum(1 for _ in self._ring_cells())
        nsp = self._sp_degree
        per_dev_tokens = (B // self._dp_degree) * (T // nsp)
        units = getattr(self.net, "_units", 0)
        shard = 2 * per_dev_tokens * units * 4           # k + v, f32
        nbytes = 3 * n_attn * shard * (nsp - 1)
        calls = 3 * n_attn * (nsp - 1)
        return nbytes, calls

    def _ring_cells(self):
        def walk(b):
            if isinstance(b, RingSelfAttention):
                yield b
            for c in b._children.values():
                yield from walk(c)
        return walk(self.net)


# -- the recipe triple ------------------------------------------------------

def make_model(vocab_size=512, seq_len=None, dense_attention=False, ctx=None,
               **kw):
    from .. import context as _ctx
    net = LongContextLM(vocab_size, max_length=seq_len,
                        dense_attention=dense_attention, **kw)
    net.initialize(ctx=ctx or _ctx.current_context())
    return net


def make_oracle(vocab_size=512, seq_len=None, ctx=None, **kw):
    """Dense O(T^2) attention — the parity reference at moderate T."""
    return make_model(vocab_size, seq_len=seq_len, dense_attention=True,
                      ctx=ctx, **kw)


def make_trainer(net, mesh, dp_axis="dp", sp_axis="sp", learning_rate=1e-3,
                 **kw):
    return LongContextTrainer(net, token_cross_entropy, optimizer="adam",
                              optimizer_params={"learning_rate":
                                                learning_rate},
                              mesh=mesh, dp_axis=dp_axis, sp_axis=sp_axis,
                              **kw)


from . import Recipe, register  # noqa: E402

register(Recipe("long_context", make_model, make_trainer, make_oracle))
