"""Expert-parallel MoE training recipe (docs/large_models.md).

``MoETrainer`` composes three shardings in ONE fused jitted step over a
{'dp': d, 'ep': e} mesh:

  - the batch is sharded over BOTH axes (dp x ep devices each hold a
    token shard — every device does forward/backward work);
  - expert parameters (tagged ``_is_moe_expert`` by the model cell) are
    sharded over 'ep' and updated LOCALLY from the all_to_all-routed
    gradients — true expert parallelism, no replication;
  - the remaining dense parameters ride the ZeRO bucket planner over 'dp'
    exactly as DataParallelTrainer's zero mode (expert leaves are
    excluded from the dp buckets; their optimizer state lives in the
    per-parameter "extras" slots, born ep-sharded).

Gradient math (the parity tests pin it): dense grads are psum'd over ep,
reduce-scattered over dp, and normalized by dp*ep — the mean over all
devices; expert grads already accumulate their cross-ep contributions
through the all_to_all VJP, so they take pmean over dp / ep only.

Everything else — StepProgram artifact cache + roofline rows, bounded
in-flight dispatch, elastic capture/restore (incl. ep-degree resharding:
expert leaves are global-shape arrays, ``_place_like`` re-lays them out) —
is inherited from DataParallelTrainer.
"""
from __future__ import annotations

import functools
from typing import List

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax
from jax.sharding import NamedSharding

from ..base import MXNetError
from ..ndarray import NDArray
from ..engine import async_feed as _feed
from .. import random as _rng
from .. import sanitize as _sanitize
from .. import telemetry as _telem
from .. import optimizer as opt_mod
from ..parallel import zero as _zero
from ..parallel import moe as _moe
from ..parallel.data_parallel import DataParallelTrainer, _make_apply_fn
from ..parallel.mesh import require_axis, P
from ..parallel.step_program import StepProgram

__all__ = ["MoETrainer", "token_cross_entropy", "make_model", "make_oracle",
           "make_trainer"]


def token_cross_entropy(logits, labels):
    """Mean token-level cross entropy in f32 — the recipe's loss."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None].astype(jnp.int32),
                               axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def _moe_cells(block, out=None):
    """Every MoEPositionwiseFFN in the tree (for wire-byte accounting)."""
    from ..models.moe_transformer import MoEPositionwiseFFN
    if out is None:
        out = []
    if isinstance(block, MoEPositionwiseFFN):
        out.append(block)
    for child in block._children.values():
        _moe_cells(child, out)
    return out


class MoETrainer(DataParallelTrainer):
    """Fused dp x ep trainer for MoE transformers (see module docstring).

    ``net`` must already be initialized; its expert parameters carry the
    ``_is_moe_expert`` tag (models/moe_transformer.py). The trainer stamps
    ``P(ep, None, ...)`` shardings onto them before the base constructor
    places parameters, so the experts are born distributed.
    """

    def __init__(self, net, loss, optimizer="adam", optimizer_params=None,
                 mesh=None, dp_axis="dp", ep_axis="ep",
                 aux_loss_weight=1e-2, comm_dtype=None, bucket_bytes=None):
        from ..parallel.mesh import current_mesh
        mesh = mesh if mesh is not None else current_mesh()
        require_axis(mesh, dp_axis, "MoETrainer data parallelism")
        self._ep_axis = ep_axis
        self._ep_degree = require_axis(mesh, ep_axis,
                                       "MoETrainer expert parallelism")
        self._aux_weight = float(aux_loss_weight)
        self._expert_flags: List[bool] = []
        self._dropped_handles: list = []
        self._a2a_cache: dict = {}
        n_expert = 0
        for p in net.collect_params().values():
            if getattr(p, "_is_moe_expert", False):
                if p.shape is None:
                    raise MXNetError(f"expert parameter {p.name} has no "
                                     "shape; initialize the net first")
                if p.shape[0] % self._ep_degree:
                    raise MXNetError(
                        f"expert parameter {p.name}: E={p.shape[0]} not "
                        f"divisible by ep={self._ep_degree}")
                p.sharding = P(ep_axis, *([None] * (len(p.shape) - 1)))
                n_expert += 1
        if not n_expert:
            raise MXNetError("net has no _is_moe_expert parameters; "
                             "MoETrainer expects a MoE model "
                             "(models/moe_transformer.py)")
        super().__init__(net, loss, optimizer=optimizer,
                         optimizer_params=optimizer_params, mesh=mesh,
                         batch_axis_name=dp_axis, dtype="float32",
                         data_spec=P((dp_axis, ep_axis)), zero_update=True,
                         bucket_bytes=bucket_bytes, comm_dtype=comm_dtype,
                         overlap_grads=False)
        # MoE-specific compile-key terms: ep layout, aux weight, wire dtype
        # (the a2a exchanges ride the same canonicalized _comm_dtype the
        # base constructor resolved for the zero collectives)
        self._step_key_base = self._step_key_base + (
            ("moe", ep_axis, self._ep_degree, self._aux_weight,
             self._comm_dtype),)
        self._program = StepProgram(
            f"moe.step[{type(net).__name__}]", self._step_key_base)

    # -- zero-mode hooks (called inside the base constructor) ----------------
    def _validate_zero(self, compression):
        """MoE relaxation of the base preconditions: expert parameters ARE
        sharded (over ep) and the batch IS sharded over both axes; any
        other parameter sharding or feature combination stays rejected."""
        self._expert_flags = [bool(getattr(p, "_is_moe_expert", False))
                              for p in self._plist]
        if compression:
            raise MXNetError("MoETrainer does not support 2-bit gradient "
                             "compression; use comm_dtype instead")
        bad = [p.name for p, s, e in zip(self._plist, self._param_shardings,
                                         self._expert_flags)
               if not e and any(ax is not None for ax in s.spec)]
        if bad:
            raise MXNetError(
                "MoETrainer shards only expert parameters (over "
                f"{self._ep_axis!r}); found other sharded params {bad[:3]}")
        sparse = [p.name for p, lz in zip(self._plist, self._lazy) if lz]
        if sparse:
            raise MXNetError("MoETrainer is incompatible with row_sparse "
                             f"lazy-update parameters ({sparse[:3]})")
        from ..optimizer.optimizer import LAMB, LARS
        if isinstance(self.optimizer, (LAMB, LARS)):
            raise MXNetError(
                f"{type(self.optimizer).__name__} per-tensor trust ratios "
                "do not decompose over flat bucket shards")

    def _init_zero_state(self):
        """Base zero-state planning minus the expert leaves: experts join
        the per-parameter extras — their (m, v, ...) state is created from
        the PLACED ep-sharded weights, so it is born distributed and the
        elastic capture sees it as ordinary ``opt.x{i}.{k}`` leaves."""
        dp_sh = NamedSharding(self.mesh, P(self.batch_axis))
        entries = [(i, w.shape, w.dtype)
                   for i, (w, t) in enumerate(zip(self._params_raw,
                                                  self._trainable))
                   if t and jnp.issubdtype(w.dtype, jnp.floating)
                   and not self._expert_flags[i]]
        self._zero_plan = _zero.plan_buckets(entries, self._dp_degree,
                                             self._bucket_bytes)
        in_bucket = frozenset(i for b in self._zero_plan for i in b.indices)
        carry = []
        for b in self._zero_plan:
            flat_w = _zero.flatten_bucket(b, self._params_raw)
            state = opt_mod.init_functional_state(self._init_fn, flat_w,
                                                  sharding=dp_sh)
            wd_dev = self._put_replicated(_zero.wd_vector(b, self._wds),
                                          dp_sh)
            carry.append((wd_dev, state))
        extra = tuple(self._init_fn(w) if (t and i not in in_bucket) else ()
                      for i, (w, t) in enumerate(zip(self._params_raw,
                                                     self._trainable)))
        self._opt_state = (tuple(carry), extra)

    # -- the fused dp x ep step body -----------------------------------------
    def _build_step_zero(self):
        aux_order = []
        apply_fn = _make_apply_fn(self.net, self._plist, train=True,
                                  aux_order_out=aux_order)
        plist = self._plist
        update_fn = self._update_fn
        loss_raw = self._loss_raw
        wds = self._wds
        trainable = self._trainable
        expert = self._expert_flags
        mesh = self.mesh
        dp_ax = self.batch_axis
        ep_ax = self._ep_axis
        ndp = self._dp_degree
        nep = self._ep_degree
        buckets = self._zero_plan
        in_bucket = frozenset(i for b in buckets for i in b.indices)
        comm = self._comm_dtype
        aux_w = self._aux_weight

        def body(params, opt_state, key, x, y, lr, t, loss_scale):
            bucket_carry, extra_state = opt_state
            dpos = lax.axis_index(dp_ax)
            epos = lax.axis_index(ep_ax)
            kk = jax.random.wrap_key_data(key.astype(jnp.uint32),
                                          impl="threefry2x32")
            # fold in the FLAT device position: the stream a device sees
            # depends only on its position in the device list, not on the
            # dp/ep factorization — the ep4-vs-ep1 parity tests rely on it
            key_local = jax.random.key_data(
                jax.random.fold_in(kk, dpos * nep + epos))

            def lossf(ps):
                with _moe.expert_axis(ep_ax, comm), \
                        _moe.collect_metrics() as mc:
                    out, aux = apply_fn(key_local, ps, x)
                pred = out if not isinstance(out, tuple) else out[0]
                task = loss_raw(pred, y)  # mean over the LOCAL token shard
                lossv = task + aux_w * mc.aux_loss()
                return lossv, (mc.dropped_total(), aux)

            (lossv, (dropped, aux)), grads = jax.value_and_grad(
                lossf, has_aux=True)(params)

            new_params = list(params)
            new_extra = list(extra_state)
            for i, (g, w, s) in enumerate(zip(grads, params, extra_state)):
                if not trainable[i] or i in in_bucket:
                    continue
                if expert[i]:
                    # this shard's grad already sums every source device's
                    # routed contribution (all_to_all VJP); dp replicas
                    # average, and /nep matches the dense grads' global
                    # mean normalization
                    gg = lax.pmean(g, dp_ax) / nep
                else:
                    gg = lax.pmean(g, (dp_ax, ep_ax))
                w2, s2 = update_fn(gg, w, s, t, lr, jnp.float32(wds[i]))
                new_params[i] = w2.astype(w.dtype)
                new_extra[i] = s2
            # dense buckets: psum over ep, reduce-scatter over dp, 1/N
            # sharded update, gather back (DataParallelTrainer zero math
            # with the extra ep reduction folded into the normalizer)
            new_carry = []
            for b, (wd_vec, st) in zip(buckets, bucket_carry):
                flat_g = lax.psum(_zero.flatten_bucket(b, grads), ep_ax)
                g_shard = _zero.reduce_scatter_bucket(
                    flat_g, dp_ax, ndp, comm) / (ndp * nep)
                w_shard = _zero.shard_slice(
                    b, _zero.flatten_bucket(b, params), dpos)
                w2, s2 = update_fn(g_shard.astype(w_shard.dtype), w_shard,
                                   st, t, lr, wd_vec)
                full = _zero.all_gather_bucket(w2.astype(w_shard.dtype),
                                               dp_ax)
                for i, arr in _zero.unflatten_bucket(b, full):
                    new_params[i] = arr.astype(params[i].dtype)
                new_carry.append((wd_vec, s2))
            glob_loss = lax.pmean(lossv, (dp_ax, ep_ax))
            glob_drop = lax.psum(dropped, (dp_ax, ep_ax))
            aux = jax.tree_util.tree_map(
                lambda v: lax.pmean(v, (dp_ax, ep_ax))
                if jnp.issubdtype(v.dtype, jnp.floating) else v, aux)
            idx_of = {id(p): i for i, p in enumerate(plist)}
            for p, v in zip(aux_order, aux):
                j = idx_of.get(id(p))
                if j is not None and not trainable[j]:
                    new_params[j] = v.astype(new_params[j].dtype)
            return (new_params, (tuple(new_carry), tuple(new_extra)),
                    glob_loss, glob_drop, aux)

        dspec = self.data_spec
        rep = P()
        dp = P(dp_ax)
        param_specs = [s.spec for s in self._param_shardings]
        extra_specs = tuple(param_specs[i] if expert[i] else rep
                            for i in range(len(self._plist)))
        return _zero.shard_map_compat(
            body, mesh=mesh,
            in_specs=(param_specs, (dp, extra_specs), rep, dspec, dspec,
                      rep, rep, rep),
            out_specs=(param_specs, (dp, extra_specs), rep, rep, rep))

    # -- dispatch ------------------------------------------------------------
    def step(self, x, y, batch_size=None):
        """One fused dp x ep step; returns the global mean loss as a
        PendingScalar. The global dropped-token count rides along as a
        device handle and is booked at ``drain()``/``sync()`` — never a
        per-step host sync."""
        xr = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yr = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        bs = batch_size or xr.shape[0]
        self.optimizer.rescale_grad = 1.0
        sig = (xr.shape, str(xr.dtype), yr.shape, str(yr.dtype))
        fn = self._get_step(sig)
        self._t += 1
        self.optimizer.num_update = self._t
        lr = _np.float32(self.optimizer.learning_rate)
        key = _np.asarray(_rng.next_key_raw())
        xr = self._put_batch(xr, NamedSharding(self.mesh, self.data_spec))
        y_spec = self.data_spec if yr.ndim >= len(self.data_spec) \
            else P(*self.data_spec[:yr.ndim])
        yr = self._put_batch(yr, NamedSharding(self.mesh, y_spec))
        scale = _np.float32(1.0)
        t_in = _np.float32(self._t)
        if not self._is_multiprocess():
            key, lr, t_in, scale = jax.device_put(
                (key, lr, t_in, scale), NamedSharding(self.mesh, P()))
        call_args = (self._params_raw, self._opt_state, key, xr, yr, lr,
                     t_in, scale)
        self._program.capture_cost(sig, fn, *call_args, kind="moe_step")
        with _telem.annotate("mx.moe.step"), _sanitize.guard():
            (self._params_raw, self._opt_state, lossv, dropped,
             aux) = fn(*call_args)
        self._window.admit(lossv)
        self._dropped_handles.append(dropped)
        if _telem._ENABLED:
            self._record_telemetry(sig, bs, 1)
        return _feed.PendingScalar(lossv)

    def drain(self):
        super().drain()
        self._flush_dropped()

    def _flush_dropped(self):
        """Book the accumulated dropped-token handles (drain/sync boundary:
        every dispatched step has completed, reading them costs nothing)."""
        handles, self._dropped_handles = self._dropped_handles, []
        if handles and _telem._ENABLED:
            _telem.record_moe_dropped(sum(int(d) for d in handles),
                                      source="moe")

    # -- telemetry -----------------------------------------------------------
    def _a2a_step_bytes(self, x_shape):
        """(bytes, calls) of one step's all_to_all traffic: per MoE cell,
        2 forward exchanges (dispatch + combine) and their 2 VJP mirrors,
        each ``all_to_all_wire_bytes`` exactly."""
        key = tuple(x_shape)
        hit = self._a2a_cache.get(key)
        if hit is None:
            n_tok = int(_np.prod(x_shape))
            n_local = n_tok // (self._dp_degree * self._ep_degree)
            total = calls = 0
            for cell in _moe_cells(self.net):
                per = _moe.all_to_all_wire_bytes(
                    n_local, cell._units, n_experts=cell._num_experts,
                    top_k=cell._top_k,
                    capacity_factor=cell._capacity_factor,
                    ep=self._ep_degree, comm_dtype=self._comm_dtype)
                total += 4 * per
                calls += 4
            hit = self._a2a_cache[key] = (total, calls)
        return hit

    def _record_telemetry(self, sig, examples, steps, flops_key=None):
        if self._ep_degree > 1:
            nbytes, calls = self._a2a_step_bytes(sig[0])
            _telem.record_comm("all_to_all", nbytes * steps, store="mesh",
                               calls=calls * steps, axis="ep")
        super()._record_telemetry(sig, examples, steps, flops_key=flops_key)


# ---------------------------------------------------------------------------
# The recipe triple
# ---------------------------------------------------------------------------

def make_model(vocab_size=512, num_experts=4, top_k=1, capacity_factor=2.0,
               dense_ffn=False, ctx=None, **kw):
    """Initialized recipe model (tiny config — scale via kwargs)."""
    from .. import context as _ctx
    from ..models import moe_transformer_tiny
    net = moe_transformer_tiny(vocab_size=vocab_size,
                               num_experts=num_experts, top_k=top_k,
                               capacity_factor=capacity_factor,
                               dense_ffn=dense_ffn, **kw)
    net.initialize(ctx=ctx or _ctx.current_context())
    return net


make_oracle = functools.partial(make_model, dense_ffn=True)


def make_trainer(net, mesh, dp_axis="dp", ep_axis="ep", learning_rate=1e-3,
                 **kw):
    return MoETrainer(net, token_cross_entropy, optimizer="adam",
                      optimizer_params={"learning_rate": learning_rate},
                      mesh=mesh, dp_axis=dp_axis, ep_axis=ep_axis, **kw)


from . import Recipe, register  # noqa: E402  (registry lives in the package)

register(Recipe("moe", make_model, make_trainer, make_oracle))
