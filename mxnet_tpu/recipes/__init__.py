"""Reference large-model training recipes (docs/large_models.md).

Each recipe is a composable (model-builder, trainer-config, parity-oracle)
triple that turns a large-model primitive into a first-class benchmarked
workload, the way ResNet/BERT exercise the dense path:

  - ``recipes.moe``:  sparse-MoE transformer with expert parallelism over
    an 'ep' mesh axis — capacity gating + aux load-balance loss, quantized
    all_to_all dispatch/combine, ZeRO-over-dp for the dense params, full
    StepProgram/roofline/elastic integration. Oracle: the same model with
    ``dense_ffn=True`` (E=1 degenerate gating matches it exactly).
  - ``recipes.long_context``: >=32k-token BERT variant on the blockwise/
    flash attention path, sequence chunking through ``DeviceFeed``.
    Oracle: the dense O(T^2) attention path at moderate T.

The subsystem is lazy — ``mxnet_tpu.recipes.moe`` imports nothing until
touched (jax-free at package import, like mxnet_tpu.elastic).
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple

__all__ = ["Recipe", "get_recipe", "list_recipes", "moe", "long_context"]


class Recipe(NamedTuple):
    """The (model-builder, trainer-config, parity-oracle) triple."""
    name: str
    build_model: Callable[..., Any]     # -> initialized HybridBlock
    build_trainer: Callable[..., Any]   # (net, mesh, **kw) -> trainer
    build_oracle: Callable[..., Any]    # -> the parity-reference model


_REGISTRY = {}


def _lazy(name):
    import importlib
    mod = importlib.import_module(f".{name}", __name__)
    globals()[name] = mod
    return mod


def __getattr__(name):
    if name in ("moe", "long_context"):
        return _lazy(name)
    raise AttributeError(f"module 'mxnet_tpu.recipes' has no attribute {name!r}")


def get_recipe(name: str) -> Recipe:
    if name not in _REGISTRY:
        if name in ("moe", "long_context"):
            _lazy(name)  # registers itself at import
        else:
            raise KeyError(f"unknown recipe {name!r}; have {list_recipes()}")
    return _REGISTRY[name]


def list_recipes():
    return ["moe", "long_context"]


def register(recipe: Recipe):
    _REGISTRY[recipe.name] = recipe
    return recipe
