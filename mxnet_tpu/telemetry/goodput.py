"""Goodput ledger: per-step wall-clock waterfall attribution.

Decomposes every wall-clock second of a training run into named
categories — where the roofline ledger (roofline.py, arXiv:2301.13062
framing) says what each compiled region *achieved*, this plane says where
the run's *time went*:

    compute               wall not attributed to any badput category
                          (derived remainder; see the reconciliation rule)
    comm_exposed          unoverlapped collective wire traffic converted
                          to seconds at peak_bytes_per_second(), split per
                          mesh axis (the PR 16 comm_axis_bytes accounting)
    feed_stall            consumer waits on an empty DeviceFeed queue
                          (mx_feed_stall_seconds_total)
    dispatch_backpressure DispatchWindow admit()/drain() block time
    snapshot              snapshot wall seconds, dispatch to manifest
                          commit (mx_checkpoint_save_seconds_total)
    compile               engine trace+compile stamps (cache_stats)
    pipeline_bubble       analytic schedule bubble fraction x the step's
                          device-bound share (set_pipeline_bubble)
    restart_downtime      boot-to-resume wall after a restart (run-level,
                          not folded into any single step's waterfall)
    other                 the reconciliation residual: seconds the
                          independently-clocked categories double-counted
                          past measured wall (e.g. the background snapshot
                          writer overlapping compute)

Reconciliation rule (the roofline-FLOP discipline): for every step record

    compute + sum(badput categories) - other == wall     (exactly)

with all values >= 0. ``other`` therefore IS the attribution error bar;
the acceptance gate keeps it <= 5% of wall.

Zero new host syncs: every category is a *delta of cumulative host-side
stamps the layers already take* (feed stall totals, window wait totals,
snapshot-writer seconds, engine compile seconds, comm byte counters),
consumed once per recorded step at DispatchWindow-admission pace through
the one ``telemetry.record_step`` funnel. The disarmed path is a single
module-flag check (the telemetry._ENABLED idiom).

Each armed host appends fixed-schema NDJSON records to an on-disk
time-series ring (``<root>/telemetry/host-<rank>.tsr``, bounded by
MXNET_TPU_GOODPUT_RING_BYTES with one ``.old`` rotation segment,
fsync-free buffered appends) that survives the process. ``aggregate()``
rides the elastic coordinator's shared root to merge every host's series
into a generation-stamped run summary with straggler detection (per-host
median step time vs the fleet median, booked as
``mx_straggler_score{rank}`` and surfaced in /statusz + the flight
recorder on eviction). ``tools/goodput_report.py`` renders a merged run
offline; docs/observability.md ("Goodput waterfall") documents the
category definitions and the CLI workflow.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ..base import MXNetError, env

__all__ = [
    "CATEGORIES", "enable", "disable", "is_enabled", "reset", "note_step",
    "set_generation", "set_pipeline_bubble", "record_restart_downtime",
    "on_eviction", "totals", "goodput_ratio", "report", "dump_json",
    "aggregate", "statusz_view", "ring_path",
]

env.declare("MXNET_TPU_GOODPUT", False, bool,
            "Arm the goodput waterfall ledger at import (implies telemetry)")
env.declare("MXNET_TPU_GOODPUT_RING_BYTES", 8 << 20, int,
            "On-disk time-series ring size per segment; the ring keeps the "
            "active segment plus one rotated .old segment")
env.declare("MXNET_TPU_STRAGGLER_SKEW", 1.75, float,
            "Straggler threshold: a host whose median step time exceeds "
            "skew x the fleet median is flagged")

# badput categories in attribution order; compute and other are derived
BADPUT = ("restart_downtime", "feed_stall", "dispatch_backpressure",
          "snapshot", "compile", "comm_exposed", "pipeline_bubble")
CATEGORIES = ("compute",) + BADPUT + ("other",)

_SCHEMA = 1

# process-boot anchor for restart-downtime accounting (module import is
# the earliest stamp available without patching the interpreter)
_PROCESS_T0 = time.perf_counter()

_LOCK = threading.RLock()

# the one flag every instrumentation site checks (telemetry._ENABLED idiom)
_ENABLED = False


class _Ring:
    """Bounded fsync-free NDJSON appender: active segment + one ``.old``
    rotation, meta header line per segment (the flight-recorder dump
    convention), so a reader can re-anchor perf-counter timestamps."""

    def __init__(self, path: str, max_bytes: int, meta: Dict[str, Any]):
        self.path = path
        self.max_bytes = int(max_bytes)
        self.meta = meta
        self._f = None
        self._n = 0

    def _open(self):
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._f = open(self.path, "a")
        self._n = self._f.tell()
        if self._n == 0:
            line = json.dumps({"k": "meta", **self.meta},
                              separators=(",", ":"))
            self._f.write(line + "\n")
            self._n += len(line) + 1

    def append(self, rec: Dict[str, Any]):
        if self._f is None:
            self._open()
        elif self._n >= self.max_bytes:
            # rotate: the previous segment survives as .old — a bounded
            # ring of two segments, never an unbounded log
            self._f.close()
            os.replace(self.path, self.path + ".old")
            self._f = None
            self._open()
        line = json.dumps(rec, separators=(",", ":"))
        self._f.write(line + "\n")
        # flush to the OS (crash-of-process safe) but never fsync: the
        # ledger must not put a disk barrier on the step path
        self._f.flush()
        self._n += len(line) + 1

    def close(self):
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None


class _Ledger:
    def __init__(self):
        self.rank = 0
        self.generation = 0
        self.steps = 0
        self.wall = 0.0
        self.totals = {c: 0.0 for c in CATEGORIES}
        self.comm_axes: Dict[str, float] = {}
        self.per_source: Dict[str, Dict[str, Any]] = {}
        self.bubble_fraction: Dict[str, float] = {}
        # cumulative upstream stamps at the last recorded step (None until
        # the first record anchors them — the record_step anchor idiom)
        self.last: Optional[Dict[str, Any]] = None
        self.last_dispatch: Dict[str, float] = {}
        self.note_anchor: Dict[str, float] = {}
        self.pending_restart = 0.0
        self.straggler: Dict[str, float] = {}
        self.ring: Optional[_Ring] = None


_L = _Ledger()


def _telem():
    from .. import telemetry as _t
    return _t


# ---------------------------------------------------------------------------
# Arming
# ---------------------------------------------------------------------------

def _resolve_rank(rank: Optional[int]) -> int:
    if rank is not None:
        return int(rank)
    v = os.environ.get("MXNET_TPU_RANK")
    if v:
        try:
            return int(v)
        except ValueError:
            pass
    # consult jax only if something else already imported it — a pure
    # host-side process (drill child) never pays the import for a label
    jx = sys.modules.get("jax")
    if jx is not None:
        try:
            return int(jx.process_index())
        except Exception:
            pass
    return 0


def enable(root: Optional[str] = None, rank: Optional[int] = None,
           ring_bytes: Optional[int] = None):
    """Arm the ledger (arms telemetry too — every category is a delta of
    telemetry stamps). With ``root`` (the elastic coordinator's shared
    root) per-step records append to ``<root>/telemetry/host-<rank>.tsr``;
    without it the ledger is in-memory only."""
    global _ENABLED
    t = _telem()
    t.enable()
    with _LOCK:
        _L.rank = _resolve_rank(rank)
        if root is not None:
            path = os.path.join(os.path.abspath(root), "telemetry",
                                f"host-{_L.rank}.tsr")
            meta = {"schema": _SCHEMA, "rank": _L.rank, "pid": os.getpid(),
                    "generation": _L.generation, "wall_time": time.time(),
                    "perf": time.perf_counter()}
            nbytes = int(env.get("MXNET_TPU_GOODPUT_RING_BYTES")
                         if ring_bytes is None else ring_bytes)
            if _L.ring is not None:
                _L.ring.close()
            _L.ring = _Ring(path, nbytes, meta)
        _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False
    with _LOCK:
        if _L.ring is not None:
            _L.ring.close()
        # re-arm re-anchors: stamps that accumulated while disarmed must
        # never be attributed to the first step after re-enable()
        _L.last = None
        _L.last_dispatch.clear()


def is_enabled() -> bool:
    return _ENABLED


def reset():
    global _L, _ENABLED
    with _LOCK:
        if _L.ring is not None:
            _L.ring.close()
        _L = _Ledger()
        _ENABLED = False


def ring_path() -> Optional[str]:
    with _LOCK:
        return _L.ring.path if _L.ring is not None else None


# ---------------------------------------------------------------------------
# Category sources (cumulative upstream stamps; all host-side)
# ---------------------------------------------------------------------------

def _fam_sum(t, name: str) -> float:
    fam = t.get_metric(name)
    return float(fam.get()) if fam is not None else 0.0


def _compile_seconds() -> float:
    try:
        from .. import engine as _engine
        return float(_engine.cache_stats().get("compile_seconds", 0.0))
    except Exception:
        return 0.0


def _comm_unoverlapped_bytes(t) -> Dict[str, float]:
    """Per-mesh-axis unoverlapped wire bytes from mx_comm_bytes_total —
    the exposed-comm numerator of the PR 16 per-axis overlap accounting."""
    fam = t.get_metric("mx_comm_bytes_total")
    if fam is None:
        return {}
    with t._LOCK:
        series = list(fam._series.items())
    out: Dict[str, float] = {}
    for lv, s in series:
        if len(lv) < 4 or lv[2] != "0":
            continue
        ax = lv[3] or "none"
        out[ax] = out.get(ax, 0.0) + getattr(s, "value", 0.0)
    return out


def _snapshot_upstream(t) -> Dict[str, Any]:
    return {
        "feed_stall": _fam_sum(t, "mx_feed_stall_seconds_total"),
        "dispatch": _fam_sum(t, "mx_dispatch_wait_seconds_total"),
        "snapshot": _fam_sum(t, "mx_checkpoint_save_seconds_total"),
        "compile": _compile_seconds(),
        "comm": _comm_unoverlapped_bytes(t),
    }


# ---------------------------------------------------------------------------
# Recording (the hot path: called from telemetry.record_step)
# ---------------------------------------------------------------------------

def set_generation(generation: int):
    """Stamp subsequent records with the coordinator's group generation
    (called from Coordinator.join/view when armed)."""
    with _LOCK:
        _L.generation = generation


def set_pipeline_bubble(source: str, fraction: float):
    """Register the analytic schedule-bubble fraction for ``source`` —
    (idle ticks / total ticks) from the 1F1B/GPipe tick counts; the ledger
    multiplies it into the step's device-bound share (the measured tick
    slope), never into feed/snapshot time."""
    with _LOCK:
        _L.bubble_fraction[source] = min(max(fraction, 0.0), 1.0)


def record_restart_downtime(outcome: str, seconds: Optional[float] = None):
    """Book boot-to-resume wall time after a restart (called from
    elastic.run.resume_or_init for resumed/resharded outcomes). Run-level:
    appended to the ring and the totals, never folded into one step's
    waterfall (it would swamp that step and read as overattribution)."""
    if not _ENABLED:
        return
    if seconds is None:
        seconds = time.perf_counter() - _PROCESS_T0
    seconds = max(float(seconds), 0.0)
    with _LOCK:
        _L.totals["restart_downtime"] += seconds
        if _L.ring is not None:
            try:
                _L.ring.append({"k": "restart", "t": round(
                    time.perf_counter(), 6), "outcome": outcome,
                    "seconds": round(seconds, 6), "gen": _L.generation})
            except OSError:
                pass
    t = _telem()
    t.counter("mx_goodput_seconds_total",
              "Wall seconds attributed by the goodput waterfall ledger",
              ("category",)).labels("restart_downtime").inc(seconds)


def note_step(source: str = "step", seconds: Optional[float] = None,
              steps: int = 1):
    """Self-anchored per-step recording for loops that do not go through
    telemetry.record_step (the drill's toy trainer): the first call only
    anchors the clock, like record_step."""
    if not _ENABLED:
        return
    now = time.perf_counter()
    with _LOCK:
        prev = _L.note_anchor.get(source)
        _L.note_anchor[source] = now
    if seconds is None:
        if prev is None:
            return
        seconds = now - prev
    _on_step(source, seconds, steps)


def _on_step(source: str, seconds: float, steps: int = 1,
             dispatch_wait: Optional[float] = None):
    """The per-step funnel (telemetry.record_step calls this when armed):
    attribute ``seconds`` of wall across the categories from deltas of
    the cumulative stamps the layers already took. Host arithmetic only —
    no device access, no clock reads beyond record_step's own."""
    t = _telem()
    wall = max(seconds, 0.0)
    cur = _snapshot_upstream(t)
    with _LOCK:
        prev, _L.last = _L.last, cur
        cats = {c: 0.0 for c in BADPUT}
        axes: Dict[str, float] = {}
        if prev is not None:
            cats["feed_stall"] = max(
                cur["feed_stall"] - prev["feed_stall"], 0.0)
            cats["snapshot"] = max(cur["snapshot"] - prev["snapshot"], 0.0)
            cats["compile"] = max(cur["compile"] - prev["compile"], 0.0)
            if dispatch_wait is not None:
                # precise per-source window wait handed down by the trainer
                last = _L.last_dispatch.get(source)
                _L.last_dispatch[source] = dispatch_wait
                if last is not None:
                    cats["dispatch_backpressure"] = max(
                        dispatch_wait - last, 0.0)
            else:
                cats["dispatch_backpressure"] = max(
                    cur["dispatch"] - prev["dispatch"], 0.0)
            bw = t.peak_bytes_per_second()
            for ax, nbytes in cur["comm"].items():
                d = nbytes - prev["comm"].get(ax, 0.0)
                if d > 0 and bw > 0:
                    axes[ax] = d / bw
            cats["comm_exposed"] = sum(axes.values())
        frac = _L.bubble_fraction.get(source, 0.0)
        if frac > 0.0:
            # the bubble lives inside the device-bound share of the step
            # (wall minus host-side stalls), per the analytic fraction
            device_share = max(wall - cats["feed_stall"] - cats["snapshot"]
                               - cats["compile"], 0.0)
            cats["pipeline_bubble"] = frac * device_share
        badput = sum(cats.values())
        compute = max(wall - badput, 0.0)
        other = max(badput - wall, 0.0)   # the double-count residual
        booked = dict(cats)
        booked["compute"] = compute
        booked["other"] = other
        _L.steps += steps
        _L.wall += wall
        for c, v in booked.items():
            _L.totals[c] += v
        for ax, v in axes.items():
            _L.comm_axes[ax] = _L.comm_axes.get(ax, 0.0) + v
        src = _L.per_source.setdefault(
            source, {"steps": 0, "wall": 0.0, "walls": []})
        src["steps"] += steps
        src["wall"] += wall
        w = src["walls"]
        w.append(wall / max(steps, 1))
        if len(w) > 4096:
            del w[:len(w) - 4096]
        total_wall, total_compute = _L.wall, _L.totals["compute"]
        gen = _L.generation
        ring = _L.ring
        if ring is not None:
            rec = {"k": "step", "t": round(time.perf_counter(), 6),
                   "step": _L.steps, "src": source, "n": steps,
                   "wall": round(wall, 9), "gen": gen,
                   "c": {c: round(v, 9) for c, v in booked.items() if v}}
            if axes:
                rec["ax"] = {a: round(v, 9) for a, v in axes.items()}
            try:
                ring.append(rec)
            except OSError:
                pass
    c = t.counter("mx_goodput_seconds_total",
                  "Wall seconds attributed by the goodput waterfall ledger",
                  ("category",))
    for cat, v in booked.items():
        if v > 0.0:
            c.labels(cat).inc(v)
    if total_wall > 0.0:
        t.gauge("mx_goodput_ratio",
                "Goodput fraction: compute seconds / wall seconds over "
                "every recorded step").set(total_compute / total_wall)


# ---------------------------------------------------------------------------
# Local views
# ---------------------------------------------------------------------------

def totals() -> Dict[str, Any]:
    """This process's cumulative waterfall: per-category seconds, wall,
    steps, per-axis exposed comm, goodput ratio."""
    with _LOCK:
        return {
            "steps": _L.steps, "wall_seconds": _L.wall,
            "generation": _L.generation, "rank": _L.rank,
            "categories": dict(_L.totals),
            "comm_exposed_axes": dict(_L.comm_axes),
            "goodput_ratio": (_L.totals["compute"] / _L.wall)
            if _L.wall > 0 else 0.0,
        }


def goodput_ratio() -> float:
    with _LOCK:
        return (_L.totals["compute"] / _L.wall) if _L.wall > 0 else 0.0


def _render_waterfall(cats: Dict[str, float], wall: float,
                      axes: Optional[Dict[str, float]] = None) -> List[str]:
    lines = []
    width = max(len(c) for c in CATEGORIES)
    for c in CATEGORIES:
        v = cats.get(c, 0.0)
        pct = 100.0 * v / wall if wall > 0 else 0.0
        bar = "#" * int(round(pct / 2))
        note = "  (overattribution residual)" if c == "other" and v else ""
        lines.append(f"  {c:<{width}}  {v:>10.4f}s  {pct:>5.1f}%  "
                     f"{bar}{note}")
        if c == "comm_exposed" and axes:
            for ax in sorted(axes):
                lines.append(f"  {'  axis=' + ax:<{width}}  "
                             f"{axes[ax]:>10.4f}s")
    return lines


def report(summary: Optional[Dict[str, Any]] = None) -> str:
    """Human waterfall table + goodput fraction. With no ``summary``
    renders this process's ledger; pass an ``aggregate()`` result to
    render a merged fleet run."""
    if summary is None:
        d = totals()
        lines = [f"=== goodput waterfall (rank {d['rank']}, "
                 f"{d['steps']} steps, {d['wall_seconds']:.3f}s wall, "
                 f"generation {d['generation']}) ==="]
        lines += _render_waterfall(d["categories"], d["wall_seconds"],
                                   d["comm_exposed_axes"])
        lines.append(f"  goodput fraction: {d['goodput_ratio']:.3f}")
        return "\n".join(lines)
    fleet = summary.get("fleet", {})
    wall = fleet.get("wall_seconds", 0.0)
    lines = [f"=== goodput waterfall (fleet: {len(summary.get('hosts', {}))}"
             f" hosts, {fleet.get('steps', 0)} steps, {wall:.3f}s wall, "
             f"generation {summary.get('generation', 0)}) ==="]
    lines += _render_waterfall(fleet.get("categories", {}), wall,
                               fleet.get("comm_exposed_axes"))
    lines.append(f"  goodput fraction: {fleet.get('goodput_ratio', 0.0):.3f}")
    strag = summary.get("straggler", {})
    if strag.get("scores"):
        lines.append("  straggler scores (median step / fleet median):")
        for rank in sorted(strag["scores"], key=int):
            flag = "  <-- STRAGGLER" \
                if int(rank) in strag.get("flagged", []) else ""
            lines.append(f"    rank {rank}: "
                         f"{strag['scores'][rank]:.2f}x{flag}")
    return "\n".join(lines)


def dump_json(path: Optional[str] = None, indent: Optional[int] = None) \
        -> str:
    """This process's ledger totals as JSON; optionally written to
    ``path`` (atomic rename)."""
    body = json.dumps(totals(), indent=indent, sort_keys=True)
    if path is not None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, path)
    return body


def statusz_view() -> Dict[str, Any]:
    """The /statusz section (telemetry.statusz merges it)."""
    if not _ENABLED:
        return {"enabled": False}
    d = totals()
    d["enabled"] = True
    with _LOCK:
        if _L.straggler:
            d["straggler_scores"] = dict(_L.straggler)
        if _L.ring is not None:
            d["ring"] = _L.ring.path
    return d


# ---------------------------------------------------------------------------
# Fleet aggregation + straggler detection
# ---------------------------------------------------------------------------

def _median(xs: List[float]) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _read_series(path: str) -> List[Dict[str, Any]]:
    recs: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    continue        # torn tail line of a killed host
    except OSError:
        pass
    return recs


def aggregate(root: str, book_metrics: bool = True) -> Dict[str, Any]:
    """Merge every host's on-disk series under ``<root>/telemetry/`` into
    a generation-stamped run summary with straggler scores.

    A host evicted mid-run leaves a partial series (possibly with a torn
    final line) — it still merges; its records carry the generation they
    were written under, so the summary has no hole. Straggler score =
    host median per-step wall / fleet median of those medians; hosts past
    MXNET_TPU_STRAGGLER_SKEW are flagged. With ``book_metrics`` (and
    telemetry armed) scores land on ``mx_straggler_score{rank}``."""
    tdir = os.path.join(os.path.abspath(root), "telemetry")
    hosts: Dict[int, Dict[str, Any]] = {}
    try:
        names = sorted(os.listdir(tdir))
    except OSError:
        names = []
    for name in names:
        if not name.startswith("host-") or ".tsr" not in name:
            continue
        try:
            rank = int(name.split("-", 1)[1].split(".")[0])
        except ValueError:
            continue
        h = hosts.setdefault(rank, {
            "rank": rank, "steps": 0, "wall_seconds": 0.0,
            "categories": {c: 0.0 for c in CATEGORIES},
            "comm_exposed_axes": {}, "walls": [],
            "generations": [], "restarts": 0})
        # both the active segment and its .old rotation merge into the
        # same per-rank bucket; the summary is order-insensitive (sums,
        # medians, max-generation), so segment read order is immaterial
        for rec in _read_series(os.path.join(tdir, name)):
            k = rec.get("k")
            if k == "step":
                h["steps"] += int(rec.get("n", 1))
                w = float(rec.get("wall", 0.0))
                h["wall_seconds"] += w
                h["walls"].append(w / max(int(rec.get("n", 1)), 1))
                for c, v in rec.get("c", {}).items():
                    if c in h["categories"]:
                        h["categories"][c] += float(v)
                for ax, v in rec.get("ax", {}).items():
                    h["comm_exposed_axes"][ax] = \
                        h["comm_exposed_axes"].get(ax, 0.0) + float(v)
                h["generations"].append(int(rec.get("gen", 0)))
            elif k == "restart":
                h["restarts"] += 1
                h["categories"]["restart_downtime"] += \
                    float(rec.get("seconds", 0.0))
            elif k == "meta":
                h.setdefault("meta", rec)
    fleet = {"steps": 0, "wall_seconds": 0.0,
             "categories": {c: 0.0 for c in CATEGORIES},
             "comm_exposed_axes": {}}
    medians: Dict[int, float] = {}
    for rank, h in sorted(hosts.items()):
        fleet["steps"] += h["steps"]
        fleet["wall_seconds"] += h["wall_seconds"]
        for c, v in h["categories"].items():
            fleet["categories"][c] += v
        for ax, v in h["comm_exposed_axes"].items():
            fleet["comm_exposed_axes"][ax] = \
                fleet["comm_exposed_axes"].get(ax, 0.0) + v
        medians[rank] = h["median_step_seconds"] = _median(h["walls"])
        gens = h.pop("generations", [])
        h["generation_range"] = [min(gens), max(gens)] if gens else [0, 0]
        h.pop("walls", None)
    fleet["goodput_ratio"] = (fleet["categories"]["compute"]
                              / fleet["wall_seconds"]) \
        if fleet["wall_seconds"] > 0 else 0.0
    fleet_median = _median([m for m in medians.values() if m > 0])
    skew = float(env.get("MXNET_TPU_STRAGGLER_SKEW"))
    scores = {str(r): (m / fleet_median if fleet_median > 0 else 0.0)
              for r, m in medians.items()}
    flagged = [r for r, m in medians.items()
               if fleet_median > 0 and m / fleet_median >= skew]
    # the run's current coordinator generation, when the shared root has
    # a control plane next to the telemetry dir
    generation = max((h["generation_range"][1] for h in hosts.values()),
                     default=0)
    try:
        with open(os.path.join(os.path.abspath(root), "coord",
                               "generation.json")) as f:
            generation = max(generation,
                             int(json.load(f).get("generation", 0)))
    except (OSError, ValueError):
        pass
    summary = {
        "schema": _SCHEMA, "generation": generation, "hosts": hosts,
        "fleet": fleet,
        "straggler": {"scores": scores, "flagged": sorted(flagged),
                      "fleet_median_step_seconds": fleet_median,
                      "skew_threshold": skew},
    }
    if book_metrics:
        t = _telem()
        if t._ENABLED:
            g = t.gauge("mx_straggler_score",
                        "Per-host median step time relative to the fleet "
                        "median (goodput.aggregate)", ("rank",))
            for r, sc in scores.items():
                g.labels(r).set(sc)
        with _LOCK:
            _L.straggler = dict(scores)
    return summary


def on_eviction(ranks: List[int], root: Optional[str] = None):
    """Surface straggler evidence when the coordinator evicts hosts: score
    the fleet from the on-disk series and drop an event into the flight
    recorder, so a post-mortem dump says whether the dead peer was the
    slow one. Incident-path only (never per step); failures are absorbed."""
    if not _ENABLED:
        return
    scores: Dict[str, float] = {}
    try:
        if root is not None:
            scores = aggregate(root)["straggler"]["scores"]
    except Exception:
        scores = {}
    from . import tracing as _tracing
    if _tracing._ENABLED:
        _tracing.event("mx.goodput.eviction",
                       ranks=[int(r) for r in ranks],
                       scores={r: round(s, 3) for r, s in scores.items()})


if env.get("MXNET_TPU_GOODPUT"):
    enable(rank=None)
