"""Cross-layer span tracing and the black-box flight recorder.

The metrics registry (mx.telemetry) and the roofline ledger answer *how
much*; this module answers *which request* and *which step*.  It is an
off-by-default tracing plane with the same discipline as metrics and
fault injection: disarmed, every call site is a single module-flag check
(``if _tracing._ENABLED:``) and nothing — no allocation, no clock read,
no lock — happens on the hot path.

Armed, layers that already carry telemetry hooks record **spans**
(named intervals with process-unique trace/span ids and parent links)
and **events** (instants: fault firings, io retries, anomalies) into
one bounded ring buffer.  The ring doubles as a black-box flight
recorder: on preemption (``elastic.run``) or an unhandled exception
(``sys.excepthook``/``threading.excepthook`` chain installed by
:func:`enable`) the last N entries are dumped as NDJSON so the moments
*before* a crash survive it.

Export surfaces:

- :func:`dump_chrome_trace` — Perfetto-loadable Chrome trace-event JSON.
  Track (event) names reuse the ``TraceAnnotation`` region names
  (``mx.dp.step``, ``mx.dp.run_steps``, ...) so the host spans line up
  by name with the device timeline captured by
  ``telemetry.trace_steps(n)``.
- :func:`dump_flight_recorder` — NDJSON, one entry per line, with a
  leading meta line carrying wall-clock ↔ perf_counter alignment.
- ``telemetry.statusz()`` / the ``/statusz`` HTTP endpoint — includes
  the last ``MXNET_TPU_STATUSZ_EVENTS`` recorder entries.

Cross-thread parent propagation is explicit: a producer captures
``tracing.current()`` (or allocates a root with :func:`new_root`) and
the worker thread adopts it with ``with tracing.attach(ctx):`` or by
passing ``parent=ctx`` to :func:`span`/:func:`record_span`.  Request
objects carry their ``(trace_id, span_id)`` tuple the same way.

The anomaly watchdog rides existing host-side values only — EWMA
step-time regression from ``telemetry.record_step`` seconds and
nonfinite-loss detection at ``PendingScalar`` sync points — so arming
it never adds a device sync.  Findings book ``mx_anomalies_total{kind}``
and write recorder events.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import math
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..base import env

__all__ = [
    "enable", "disable", "is_enabled",
    "span", "record_span", "event",
    "current", "attach", "new_root",
    "spans", "recent", "set_max_spans", "reset",
    "dump_chrome_trace", "dump_flight_recorder",
    "watch_step_time", "check_loss", "install_crash_hooks",
]

env.declare("MXNET_TPU_TRACING", False, bool,
            "Arm the span-tracing plane at import (tracing.enable() at "
            "runtime). Disarmed call sites are a single flag check.")
env.declare("MXNET_TPU_TRACING_MAX_SPANS", 100_000, int,
            "Flight-recorder ring capacity (completed spans + events); "
            "same bounding convention as MXNET_PROFILER_MAX_EVENTS.")
env.declare("MXNET_TPU_FLIGHT_RECORDER", "mx_flight_recorder.ndjson", str,
            "Default path for the NDJSON flight-recorder dump (preemption, "
            "crash hook, dump_flight_recorder() without a path).")
env.declare("MXNET_TPU_STATUSZ_EVENTS", 32, int,
            "How many trailing recorder entries /statusz reports.")
env.declare("MXNET_TPU_ANOMALY_STEP_RATIO", 2.5, float,
            "Watchdog: a step slower than ratio x EWMA (after warmup) books "
            "mx_anomalies_total{kind=step_time_regression}.")
env.declare("MXNET_TPU_ANOMALY_WARMUP", 10, int,
            "Watchdog: steps per source before regression checks arm "
            "(EWMA needs a baseline; compile steps would false-positive).")

_ENABLED = bool(env.get("MXNET_TPU_TRACING"))
_LOCK = threading.Lock()
_RING: "deque[Dict[str, Any]]" = deque(
    maxlen=max(int(env.get("MXNET_TPU_TRACING_MAX_SPANS")), 0))
_TLS = threading.local()
_IDS = itertools.count(1)
# Process-unique prefix: pid + 4 random bytes so ids from different
# processes (or restarts of the same pid) never collide in merged dumps.
_PREFIX = "%x-%08x" % (os.getpid(),
                       int.from_bytes(os.urandom(4), "big"))

# EWMA smoothing for the step-time watchdog.
_WD_ALPHA = 0.1
_WD: Dict[str, List[float]] = {}  # source -> [count, ewma]

_NULL = contextlib.nullcontext()  # shared, reusable, reentrant


# ---------------------------------------------------------------------------
# Arming
# ---------------------------------------------------------------------------

def enable() -> None:
    """Arm tracing and install the crash-dump excepthook chain."""
    global _ENABLED
    _ENABLED = True
    install_crash_hooks()


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def is_enabled() -> bool:
    return _ENABLED


# ---------------------------------------------------------------------------
# Ids and thread-local context
# ---------------------------------------------------------------------------

def _next_id() -> str:
    return format(next(_IDS), "x")


def new_root(name: str = "") -> Tuple[str, str]:
    """Allocate a fresh (trace_id, span_id) root context without recording
    anything. Use when the root span's duration is only known later (e.g. a
    serving request records its root at completion) or as a grouping parent
    for a worker thread's spans."""
    trace_id = "%s-%s" % (_PREFIX, _next_id())
    if name:
        trace_id = "%s-%s" % (trace_id, name)
    return (trace_id, _next_id())


def current() -> Optional[Tuple[str, str]]:
    """The innermost open (trace_id, span_id) on this thread, or None."""
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def attach(ctx: Optional[Tuple[str, str]]):
    """Adopt a context captured on another thread: spans opened inside the
    block parent under ``ctx``. No-op when disarmed or ``ctx`` is None."""
    if not _ENABLED or ctx is None:
        yield None
        return
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    stack.append((ctx[0], ctx[1]))
    try:
        yield ctx
    finally:
        stack.pop()


def _resolve_parent(parent) -> Tuple[str, Optional[str]]:
    """(trace_id, parent_span_id) from an explicit parent, the thread-local
    stack, or a fresh root trace."""
    if parent is not None:
        if isinstance(parent, _Span):
            return parent.trace_id, parent.span_id
        return parent[0], parent[1]
    cur = current()
    if cur is not None:
        return cur[0], cur[1]
    return "%s-%s" % (_PREFIX, _next_id()), None


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------

class _Span:
    """An open span; context manager. Completed on exit into the ring."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs", "_t0")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict[str, Any]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self._t0 = 0.0

    @property
    def context(self) -> Tuple[str, str]:
        return (self.trace_id, self.span_id)

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def __enter__(self) -> "_Span":
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        stack.append((self.trace_id, self.span_id))
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        t1 = time.perf_counter()
        stack = getattr(_TLS, "stack", None)
        if stack:
            stack.pop()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        _append({"kind": "span", "name": self.name,
                 "trace_id": self.trace_id, "span_id": self.span_id,
                 "parent_id": self.parent_id, "ts": self._t0,
                 "dur": t1 - self._t0, "thread": threading.get_ident(),
                 "attrs": self.attrs})


def span(name: str, parent=None, **attrs):
    """Context manager recording a span on exit. Disarmed: returns a shared
    nullcontext (no allocation). ``parent`` is an explicit (trace_id,
    span_id) tuple or open span; default is the thread-local current span,
    else a fresh root trace."""
    if not _ENABLED:
        return _NULL
    trace_id, parent_id = _resolve_parent(parent)
    return _Span(name, trace_id, _next_id(), parent_id, attrs)


def record_span(name: str, t_start: float, t_end: float, parent=None,
                ctx: Optional[Tuple[str, str]] = None,
                **attrs) -> Optional[Tuple[str, str]]:
    """Record a completed span from timestamps already in hand (no clock
    reads here — callers on measured paths reuse stamps they already took).
    ``ctx`` pre-assigns this span's own (trace_id, span_id) — used when the
    id was allocated earlier (e.g. a serving request's root span). Returns
    the span's context for chaining children."""
    if not _ENABLED:
        return None
    if ctx is not None:
        trace_id, span_id = ctx
        parent_id = parent[1] if parent is not None else None
    else:
        trace_id, parent_id = _resolve_parent(parent)
        span_id = _next_id()
    _append({"kind": "span", "name": name, "trace_id": trace_id,
             "span_id": span_id, "parent_id": parent_id, "ts": t_start,
             "dur": t_end - t_start, "thread": threading.get_ident(),
             "attrs": attrs})
    return (trace_id, span_id)


def event(name: str, parent=None, **attrs) -> Optional[Tuple[str, str]]:
    """Record an instant recorder event (fault firing, io retry, anomaly)."""
    if not _ENABLED:
        return None
    trace_id, parent_id = _resolve_parent(parent)
    span_id = _next_id()
    _append({"kind": "event", "name": name, "trace_id": trace_id,
             "span_id": span_id, "parent_id": parent_id,
             "ts": time.perf_counter(), "dur": 0.0,
             "thread": threading.get_ident(), "attrs": attrs})
    return (trace_id, span_id)


def _append(entry: Dict[str, Any]) -> None:
    # Deliberately lock-free: deque.append with maxlen is atomic under the
    # GIL, and this is the armed hot path — serving records ~6 entries per
    # request from 3+ threads, so a shared lock here turns the recorder
    # into a contention point (measured ~25% closed-loop throughput loss).
    # Readers (spans()) retry on the concurrent-mutation RuntimeError.
    _RING.append(entry)  # GIL-atomic  # mxlint: disable=lock-discipline


# ---------------------------------------------------------------------------
# Ring access
# ---------------------------------------------------------------------------

def spans() -> List[Dict[str, Any]]:
    """Snapshot of the recorder ring (oldest first). Writers are lock-free
    (see _append), so a snapshot taken mid-append can raise "deque mutated
    during iteration" — retry; the window is a single append."""
    for _ in range(64):
        try:
            return list(_RING)
        except RuntimeError:
            continue
    return []  # writer storm: the flight recorder prefers empty to hanging


def recent(n: Optional[int] = None) -> List[Dict[str, Any]]:
    """The trailing ``n`` entries (default MXNET_TPU_STATUSZ_EVENTS)."""
    if n is None:
        n = int(env.get("MXNET_TPU_STATUSZ_EVENTS"))
    entries = spans()
    if n <= 0 or n >= len(entries):
        return entries
    return entries[-n:]


def set_max_spans(n: int) -> None:
    """Re-cap the ring, keeping the newest entries (mirror of
    profiler.set_max_events — the shared bounding convention)."""
    global _RING
    with _LOCK:  # excludes concurrent re-cap/reset; appends are atomic
        _RING = deque(spans(), maxlen=max(int(n), 0))


def reset() -> None:
    """Drop recorded entries and watchdog state (telemetry.reset() calls
    this; arming state and ids are untouched)."""
    with _LOCK:
        _RING.clear()
        _WD.clear()


# ---------------------------------------------------------------------------
# Export surfaces
# ---------------------------------------------------------------------------

def dump_chrome_trace(path: str) -> str:
    """Write the ring as Chrome trace-event JSON (Perfetto-loadable).

    Span names are the track names; the trainer's dispatch spans reuse the
    ``TraceAnnotation`` region names (``mx.dp.step``, ``mx.dp.run_steps``)
    so this file and the ``trace_steps(n)`` device timeline line up by
    name. Timestamps are perf_counter microseconds, matching
    ``profiler.dump()``."""
    events = []
    for e in spans():
        out = {"name": e["name"], "cat": "mx." + e["kind"],
               "ts": e["ts"] * 1e6, "pid": 0, "tid": e["thread"],
               "args": dict(e["attrs"], trace_id=e["trace_id"],
                            span_id=e["span_id"],
                            parent_id=e["parent_id"])}
        if e["kind"] == "span":
            out["ph"] = "X"
            out["dur"] = e["dur"] * 1e6
        else:
            out["ph"] = "i"
            out["s"] = "t"
        events.append(out)
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path


def dump_flight_recorder(path: Optional[str] = None,
                         reason: str = "manual") -> str:
    """Write the ring as NDJSON: a meta line (reason, pid, wall-clock ↔
    perf_counter anchor), then one entry per line, oldest first. This is
    the black-box dump taken on preemption and by the crash hooks."""
    if path is None:
        path = str(env.get("MXNET_TPU_FLIGHT_RECORDER"))
    entries = spans()
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "meta", "reason": reason,
                            "pid": os.getpid(), "wall_time": time.time(),
                            "perf_counter": time.perf_counter(),
                            "entries": len(entries)}) + "\n")
        for e in entries:
            f.write(json.dumps(e, default=str) + "\n")
    return path


# ---------------------------------------------------------------------------
# Crash hooks (unhandled-step-exception dump)
# ---------------------------------------------------------------------------

_HOOKS_INSTALLED = [False]


def install_crash_hooks() -> None:
    """Chain sys.excepthook + threading.excepthook to dump the flight
    recorder on an unhandled exception (main thread or any worker —
    dispatcher, producer, snapshot writer). Idempotent; previous hooks
    still run."""
    with _LOCK:
        if _HOOKS_INSTALLED[0]:
            return
        _HOOKS_INSTALLED[0] = True
    prev_sys = sys.excepthook

    def _sys_hook(exc_type, exc, tb):
        _crash_dump("unhandled:%s" % getattr(exc_type, "__name__", "?"))
        prev_sys(exc_type, exc, tb)

    sys.excepthook = _sys_hook
    prev_thread = threading.excepthook

    def _thread_hook(args):
        _crash_dump("thread:%s" % getattr(args.exc_type, "__name__", "?"))
        prev_thread(args)

    threading.excepthook = _thread_hook


def _crash_dump(reason: str) -> None:
    try:
        if _ENABLED and len(_RING):
            dump_flight_recorder(reason=reason)
    except Exception:  # never let the dump mask the original failure
        pass


# ---------------------------------------------------------------------------
# Anomaly watchdog
# ---------------------------------------------------------------------------

def watch_step_time(seconds: float, source: str = "step") -> None:
    """EWMA step-time regression detector. Fed per-step host-side seconds
    from telemetry.record_step — values the metrics plane already computed,
    so no new syncs or clock reads. After MXNET_TPU_ANOMALY_WARMUP samples
    per source, a step slower than MXNET_TPU_ANOMALY_STEP_RATIO x EWMA
    books an anomaly; the sample still updates the EWMA so a genuine
    regime change (bigger batch) stops alerting after a few steps."""
    if not _ENABLED:
        return
    warmup = int(env.get("MXNET_TPU_ANOMALY_WARMUP"))
    ratio = env.get("MXNET_TPU_ANOMALY_STEP_RATIO")
    with _LOCK:
        state = _WD.get(source)
        if state is None:
            state = _WD[source] = [0.0, 0.0]
        count, ewma = state
        fire = count >= warmup and ewma > 0.0 and seconds > ratio * ewma
        state[0] = count + 1.0
        state[1] = seconds if count == 0.0 \
            else ewma + _WD_ALPHA * (seconds - ewma)
    if fire:
        _anomaly("step_time_regression", source=source,
                 seconds=seconds, ewma=ewma, ratio=ratio)


def check_loss(value: float, source: str = "step") -> None:
    """Nonfinite-loss detector. Called at PendingScalar/drain sync points
    with a host float the caller already materialised — detection piggybacks
    on syncs that were happening anyway."""
    if not _ENABLED:
        return
    try:
        if math.isfinite(value):
            return
    except (TypeError, ValueError):
        return
    _anomaly("nonfinite_loss", source=source, value=repr(value))


def _anomaly(kind: str, **attrs) -> None:
    event("mx.anomaly." + kind, kind=kind, **attrs)
    from .. import telemetry as _telem
    _telem.counter(
        "mx_anomalies_total",
        "Anomalies flagged by the tracing watchdog (EWMA step-time "
        "regression, nonfinite loss)", ("kind",)).labels(kind).inc()


if _ENABLED:
    install_crash_hooks()
