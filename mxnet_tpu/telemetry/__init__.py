"""Unified telemetry: a process-wide metrics registry every subsystem
reports into, plus Prometheus-text / JSON export.

The reference framework's runtime is legible through the profiler's
aggregate-stats table and KVStore-level comms visibility; this module is the
unified layer on top of those signals (ROADMAP: "as fast as the hardware
allows" is unverifiable without them):

  - **training-step metrics** — step time, examples/sec, and an MFU/roofline
    estimate derived from ``cost_analysis()`` FLOPs captured when the engine
    builds a compiled artifact (`engine.estimate_cost`). Fed by
    ``gluon.Trainer.step``, ``Module.fit``, and the fused
    ``parallel.*Trainer`` steps.
  - **collective-comms accounting** — bytes moved / calls / wall seconds per
    kvstore push/pull/pushpull and per fused-step gradient all-reduce, with
    ``jax.profiler.TraceAnnotation`` regions so the same boundaries show up
    inside xplane traces (TensorBoard/XProf).
  - **memory watermarks** — live device-buffer bytes and the process peak,
    sampled per step while enabled.
  - **export** — ``scrape()`` (Prometheus text), ``scrape_json()``,
    ``report()`` (human table unifying the profiler aggregate table and the
    compilation-cache counters), and ``start_http_server()`` for a real
    ``GET /metrics`` endpoint.

The registry is OFF by default. Every instrumentation site guards on the
module attribute ``_ENABLED`` (the same one-check-per-call idiom as
``ops/registry.py:_profile_hook``), so the disabled path costs one dict
lookup + branch; ``BENCH_SCENARIO=telemetry_overhead`` in bench.py proves
the enabled path stays under 2% of eager step time.
"""
from __future__ import annotations

import contextlib
import functools
import json
import sys
import threading
import time
from bisect import bisect_left
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError, env

__all__ = [
    "enable", "disable", "is_enabled", "counter", "gauge", "histogram",
    "get_metric", "reset", "collect", "scrape", "scrape_json", "report",
    "record_step", "record_comm", "comm_scope", "instrument_comm",
    "record_optimizer_state", "payload_bytes", "sample_memory", "peak_flops",
    "peak_bytes_per_second", "ridge_point", "roofline", "trace_steps",
    "trace_active",
    "record_feed_depth", "record_feed_stall", "record_inflight",
    "record_dispatch_wait",
    "record_checkpoint_save", "record_resume", "record_moe_dropped",
    "set_epoch", "timed", "annotate", "start_http_server",
    "stop_http_server", "DEFAULT_LATENCY_BUCKETS", "record_serving_enqueue",
    "record_serving_queue_depth", "record_serving_dispatch",
    "record_serving_completion", "record_fault_injected", "record_io_retry",
    "record_request_shed", "record_feed_producer_leak",
    "record_feed_producer_restart", "record_serving_queue_wait",
    "record_hosts_live", "record_commit_barrier", "record_hang_watchdog",
    "statusz", "tracing", "goodput",
]

env.declare("MXNET_TELEMETRY", False, bool,
            "Enable the telemetry registry at import")
env.declare("MXNET_TELEMETRY_MAX_SERIES", 512, int,
            "Max label combinations kept per metric family; excess series "
            "are dropped and counted in mx_telemetry_dropped_series_total")
env.declare("MXNET_TELEMETRY_PEAK_FLOPS", 0.0, float,
            "Roofline peak FLOP/s used for the MFU gauge; overrides the "
            "per-device-kind table (set this on CPU, where XLA's cost model "
            "has no meaningful peak)")
env.declare("MXNET_TELEMETRY_PEAK_BYTES", 0.0, float,
            "Roofline peak memory bandwidth (bytes/s) for the per-region "
            "ledger; overrides the per-device-kind HBM table (set this on "
            "CPU, where the 50 GB/s anchor is only an A/B reference)")
env.declare("MXNET_TPU_TRACE_DIR", "", str,
            "Default logdir for telemetry.trace_steps() device-trace "
            "capture (xplane, viewable in TensorBoard/XProf)")

_LOCK = threading.RLock()
_FAMILIES: "OrderedDict[str, MetricFamily]" = OrderedDict()

# the one flag every instrumentation site checks (module-attribute lookup +
# branch while disabled — the _profile_hook None-check idiom)
_ENABLED = bool(env.get("MXNET_TELEMETRY"))


def enable():
    """Turn instrumentation on (all sites start reporting)."""
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def is_enabled() -> bool:
    return _ENABLED


# process-rank label for multi-host scrapes: "" (single process) leaves
# every family's label set — and therefore the exposition — byte-identical
# to the single-host build; a nonempty value is appended as a TRAILING
# "host" label, so MetricFamily.get()'s prefix aggregation keeps every
# existing reader working unchanged.
_HOST_LABEL: List[Optional[str]] = [None]


def _host_label() -> str:
    """Resolve (once) the process-rank label value. Consults jax only if
    something else already imported it — a multi-host job necessarily
    initialized jax.distributed, while pure host-side processes (the
    elastic drill's children) must never pay a jax import for a label."""
    v = _HOST_LABEL[0]
    if v is None:
        v = ""
        jx = sys.modules.get("jax")
        if jx is not None:
            try:
                if int(jx.process_count()) > 1:
                    v = str(int(jx.process_index()))
            except Exception:
                v = ""
        with _LOCK:
            _HOST_LABEL[0] = v
    return v


# ---------------------------------------------------------------------------
# Metric model: family (name + label names) -> labeled series
# ---------------------------------------------------------------------------

def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _escape_help(v: str) -> str:
    # HELP-line escaping per the exposition format: backslash and newline
    # only (quotes are legal in help text). A doc with a raw newline would
    # otherwise split the HELP line and corrupt the whole scrape.
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def _fmt_labels(names: Tuple[str, ...], values: Tuple[str, ...],
                extra: str = "") -> str:
    parts = [f'{n}="{_escape(v)}"' for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _NullSeries:
    """Returned past the cardinality cap: absorbs writes silently."""

    def inc(self, n=1):
        pass

    def dec(self, n=1):
        pass

    def set(self, v):
        pass

    def set_max(self, v):
        pass

    def observe(self, v):
        pass


_NULL = _NullSeries()


class _CounterSeries:
    __slots__ = ("label_values", "value")

    def __init__(self, label_values):
        self.label_values = label_values
        self.value = 0.0

    def inc(self, n=1):
        if n < 0:
            raise MXNetError("counters only go up; use a gauge")
        with _LOCK:
            self.value += n


class _GaugeSeries:
    __slots__ = ("label_values", "value")

    def __init__(self, label_values):
        self.label_values = label_values
        self.value = 0.0

    def set(self, v):
        with _LOCK:
            self.value = float(v)

    def set_max(self, v):
        """Watermark update: keep the running maximum."""
        with _LOCK:
            self.value = max(self.value, float(v))

    def inc(self, n=1):
        with _LOCK:
            self.value += n

    def dec(self, n=1):
        with _LOCK:
            self.value -= n


class _HistogramSeries:
    __slots__ = ("label_values", "buckets", "counts", "sum", "count")

    def __init__(self, label_values, buckets):
        self.label_values = label_values
        self.buckets = buckets            # sorted upper bounds, no +Inf
        self.counts = [0] * (len(buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v):
        v = float(v)
        with _LOCK:
            self.counts[bisect_left(self.buckets, v)] += 1
            self.sum += v
            self.count += 1


class MetricFamily:
    kind = "untyped"
    _series_cls = _GaugeSeries

    def __init__(self, name: str, doc: str = "",
                 labelnames: Sequence[str] = (),
                 max_series: Optional[int] = None):
        self.name = name
        self.doc = doc
        self.labelnames = tuple(labelnames)
        self.max_series = max_series if max_series is not None \
            else int(env.get("MXNET_TELEMETRY_MAX_SERIES"))
        self._series: Dict[Tuple[str, ...], Any] = {}
        self.dropped = 0

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise MXNetError("pass label values positionally OR by name")
            try:
                values = tuple(str(kv[n]) for n in self.labelnames)
            except KeyError as e:
                raise MXNetError(
                    f"metric {self.name} missing label {e}") from None
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise MXNetError(
                f"metric {self.name} takes labels {self.labelnames}, "
                f"got {values}")
        s = self._series.get(values)
        if s is None:
            with _LOCK:
                s = self._series.get(values)
                if s is None:
                    if len(self._series) >= self.max_series:
                        # cap label cardinality: drop (and count) instead of
                        # letting a runaway label explode scrape size
                        self.dropped += 1
                        return _NULL
                    s = self._make_series(values)
                    self._series[values] = s
        return s

    def _make_series(self, values):
        return self._series_cls(values)

    def _default(self):
        return self.labels(*(("",) * len(self.labelnames))) \
            if self.labelnames else self.labels()

    # family-level convenience for label-less metrics
    def inc(self, n=1):
        self._default().inc(n)

    def dec(self, n=1):
        self._default().dec(n)

    def set(self, v):
        self._default().set(v)

    def set_max(self, v):
        self._default().set_max(v)

    def observe(self, v):
        self._default().observe(v)

    def get(self, *values) -> float:
        """Exact series value — or, with FEWER label values than the family
        has labelnames, the sum over every series matching that label
        prefix (Prometheus-style aggregation over the remaining labels, so
        readers written before a family grew a label keep working)."""
        values = tuple(str(v) for v in values)
        if len(values) < len(self.labelnames):
            with _LOCK:
                series = list(self._series.items())
            return sum(getattr(s, "value", getattr(s, "sum", 0.0))
                       for lv, s in series if lv[:len(values)] == values)
        s = self._series.get(values)
        if s is None:
            return 0.0
        return getattr(s, "value", getattr(s, "sum", 0.0))

    def _render(self, out: List[str]):
        out.append(f"# HELP {self.name} {_escape_help(self.doc)}")
        out.append(f"# TYPE {self.name} {self.kind}")
        with _LOCK:
            series = list(self._series.values())
        for s in series:
            out.append(f"{self.name}"
                       f"{_fmt_labels(self.labelnames, s.label_values)}"
                       f" {s.value}")

    def _as_dict(self):
        with _LOCK:
            return {
                "type": self.kind, "doc": self.doc,
                "series": [
                    {"labels": dict(zip(self.labelnames, s.label_values)),
                     "value": s.value}
                    for s in self._series.values()],
            }


class CounterFamily(MetricFamily):
    kind = "counter"
    _series_cls = _CounterSeries


class GaugeFamily(MetricFamily):
    kind = "gauge"
    _series_cls = _GaugeSeries


# seconds-scale spacing: 50us .. ~100s
_DEFAULT_BUCKETS = tuple(5e-5 * (2.5 ** i) for i in range(13))


class HistogramFamily(MetricFamily):
    kind = "histogram"

    def __init__(self, name, doc="", labelnames=(), buckets=None,
                 max_series=None):
        super().__init__(name, doc, labelnames, max_series)
        self.buckets = sorted(float(b) for b in (buckets or _DEFAULT_BUCKETS))

    def _make_series(self, values):
        return _HistogramSeries(values, self.buckets)

    def _render(self, out: List[str]):
        out.append(f"# HELP {self.name} {_escape_help(self.doc)}")
        out.append(f"# TYPE {self.name} histogram")
        with _LOCK:
            series = [(s.label_values, list(s.counts), s.sum, s.count)
                      for s in self._series.values()]
        for lv, counts, total, count in series:
            acc = 0
            for ub, c in zip(self.buckets, counts):
                acc += c
                le = 'le="%g"' % ub
                out.append(f"{self.name}_bucket"
                           f"{_fmt_labels(self.labelnames, lv, le)} {acc}")
            inf = 'le="+Inf"'
            out.append(f"{self.name}_bucket"
                       f"{_fmt_labels(self.labelnames, lv, inf)} {count}")
            out.append(f"{self.name}_sum"
                       f"{_fmt_labels(self.labelnames, lv)} {total}")
            out.append(f"{self.name}_count"
                       f"{_fmt_labels(self.labelnames, lv)} {count}")

    def _as_dict(self):
        with _LOCK:
            return {
                "type": "histogram", "doc": self.doc,
                "buckets": self.buckets,
                "series": [
                    {"labels": dict(zip(self.labelnames, s.label_values)),
                     "counts": list(s.counts), "sum": s.sum, "count": s.count}
                    for s in self._series.values()],
            }


def _family(cls, name, doc, labelnames, **kw):
    with _LOCK:
        fam = _FAMILIES.get(name)
        if fam is None:
            fam = _FAMILIES[name] = cls(name, doc, labelnames, **kw)
        elif type(fam) is not cls:
            raise MXNetError(
                f"metric {name!r} already registered as {fam.kind}")
        return fam


def counter(name, doc="", labelnames=(), max_series=None) -> CounterFamily:
    """Get-or-create a monotonically increasing counter family."""
    return _family(CounterFamily, name, doc, labelnames,
                   max_series=max_series)


def gauge(name, doc="", labelnames=(), max_series=None) -> GaugeFamily:
    return _family(GaugeFamily, name, doc, labelnames, max_series=max_series)


def histogram(name, doc="", labelnames=(), buckets=None,
              max_series=None) -> HistogramFamily:
    with _LOCK:
        fam = _FAMILIES.get(name)
        if fam is None:
            fam = _FAMILIES[name] = HistogramFamily(
                name, doc, labelnames, buckets, max_series)
        elif not isinstance(fam, HistogramFamily):
            raise MXNetError(
                f"metric {name!r} already registered as {fam.kind}")
        return fam


def get_metric(name) -> Optional[MetricFamily]:
    return _FAMILIES.get(name)


def reset():
    """Drop every registered family and all step/memory bookkeeping,
    including the per-region roofline ledger (tests; a long-lived server
    should scrape, not reset)."""
    global _mem_peak
    with _LOCK:
        _FAMILIES.clear()
        _STEP_ANCHOR.clear()
        _mem_peak = 0.0
        _HOST_LABEL[0] = None
    from . import roofline as _roofline
    _roofline.reset()
    from . import tracing as _tracing
    _tracing.reset()
    from . import goodput as _goodput
    _goodput.reset()


# ---------------------------------------------------------------------------
# Roofline peak for the MFU gauge
# ---------------------------------------------------------------------------

# nominal bf16 peak FLOP/s by device_kind substring (BASELINE.md / bench.py)
_PEAK_TABLE = (
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 46e12), ("v6", 918e12),
)
# With no override and no recognized accelerator (CPU CI), MFU is reported
# against this nominal anchor so the gauge exists and A/B deltas are
# comparable — the absolute value is NOT a hardware utilization claim
# (docs/observability.md, "MFU methodology").
_FALLBACK_PEAK = 1e12
_peak_cache: List[Optional[float]] = [None]


def peak_flops() -> float:
    """Peak FLOP/s the MFU gauge divides by: env override, else a
    device_kind table, else a documented 1 TF/s CPU anchor."""
    ov = float(env.get("MXNET_TELEMETRY_PEAK_FLOPS"))
    if ov > 0:
        return ov
    with _LOCK:
        if _peak_cache[0] is None:
            peak = _FALLBACK_PEAK
            try:
                import jax
                kind = jax.devices()[0].device_kind.lower()
                for sub, p in _PEAK_TABLE:
                    if sub in kind:
                        peak = p
                        break
            except Exception:
                pass
            _peak_cache[0] = peak
        return _peak_cache[0]


# nominal HBM bandwidth (bytes/s) by device_kind substring — the roofline
# denominator for the bytes axis (same resolution order as peak_flops)
_BW_TABLE = (
    ("v5 lite", 819e9), ("v5e", 819e9), ("v5p", 2765e9),
    ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9), ("v6", 1640e9),
)
# documented CPU anchor: ~DDR-class bandwidth so the ledger's ratios and
# ridge point stay meaningful for A/B deltas on CI hosts (with the 1 TF/s
# FLOPs anchor the ridge sits at 20 FLOP/byte; not a hardware claim —
# docs/observability.md, "Peak overrides")
_FALLBACK_BYTES_PER_S = 50e9
_peak_bw_cache: List[Optional[float]] = [None]


def peak_bytes_per_second() -> float:
    """Peak memory bandwidth the per-region roofline ledger divides by:
    ``MXNET_TELEMETRY_PEAK_BYTES`` override, else a device_kind HBM table,
    else the documented 50 GB/s CPU anchor."""
    ov = float(env.get("MXNET_TELEMETRY_PEAK_BYTES"))
    if ov > 0:
        return ov
    with _LOCK:
        if _peak_bw_cache[0] is None:
            bw = _FALLBACK_BYTES_PER_S
            try:
                import jax
                kind = jax.devices()[0].device_kind.lower()
                for sub, b in _BW_TABLE:
                    if sub in kind:
                        bw = b
                        break
            except Exception:
                pass
            _peak_bw_cache[0] = bw
        return _peak_bw_cache[0]


def ridge_point() -> float:
    """Arithmetic intensity (FLOP/byte) where the roofline's bandwidth
    slope meets the compute ceiling; regions below it are memory-bound."""
    return peak_flops() / peak_bytes_per_second()


# ---------------------------------------------------------------------------
# Programmatic device-trace capture (xplane timeline)
# ---------------------------------------------------------------------------

# [steps remaining, active logdir]; armed by trace_steps(), decremented by
# record_step() so the capture stops itself after n recorded steps without
# any extra sync point in the loop
_TRACE = [0, None]


def trace_steps(n: int, logdir: Optional[str] = None) -> str:
    """Start a ``jax.profiler`` device trace (xplane; TensorBoard/XProf)
    and stop it automatically after the next ``n`` recorded training steps.
    ``logdir`` defaults to ``MXNET_TPU_TRACE_DIR``, else a temp directory.
    The existing ``TraceAnnotation`` region names (``mx.dp.step``,
    ``mx.comm.*``) land inside the captured timeline, so ledger rows map
    onto trace spans by name. Returns the logdir."""
    import tempfile

    import jax
    d = logdir or str(env.get("MXNET_TPU_TRACE_DIR")) or None
    if not d:
        d = tempfile.mkdtemp(prefix="mx_trace_")
    import os as _os
    _os.makedirs(d, exist_ok=True)
    with _LOCK:
        if _TRACE[1] is not None:
            raise MXNetError(f"a trace is already active in {_TRACE[1]}")
        jax.profiler.start_trace(d)
        _TRACE[0], _TRACE[1] = max(int(n), 1), d
    return d


def trace_active() -> Optional[str]:
    """The active capture's logdir, or None."""
    return _TRACE[1]


def _trace_tick(steps: int = 1):
    """Count recorded steps against an armed capture; stops the trace when
    the budget is spent. Host-side bookkeeping only."""
    stop = False
    with _LOCK:
        if _TRACE[1] is None:
            return
        _TRACE[0] -= steps
        if _TRACE[0] <= 0:
            _TRACE[1] = None
            stop = True
    if stop:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Training-step recording
# ---------------------------------------------------------------------------

# source -> (last perf_counter stamp, engine flops_executed at that stamp)
_STEP_ANCHOR: Dict[str, Tuple[float, float]] = {}


def _engine_flops() -> float:
    try:
        from .. import engine as _engine
        return float(_engine.cache_stats().get("flops_executed", 0.0))
    except Exception:
        return 0.0


def record_step(examples: int, source: str = "trainer", steps: int = 1,
                seconds: Optional[float] = None,
                flops_per_step: Optional[float] = None,
                lr: Optional[float] = None,
                dispatch_wait_seconds: Optional[float] = None):
    """Record `steps` completed training steps covering `examples` examples.

    With seconds=None the duration is the wall time since the previous
    record_step for the same `source` (the first call only anchors the
    clock) — the once-per-iteration sync point measures the WHOLE loop
    (forward+backward+update), the way Speedometer does. flops_per_step
    defaults to the engine's executed-FLOPs counter delta (compiled-artifact
    cost_analysis accounting), which yields the MFU estimate.

    ``dispatch_wait_seconds`` is the caller's CUMULATIVE DispatchWindow
    block time (trainers pass ``self._window.wait_seconds``): the goodput
    ledger deltas it into the step's dispatch_backpressure category —
    a host float the window already accumulated, no extra clock read.
    """
    now = time.perf_counter()
    eng_flops = _engine_flops() if flops_per_step is None else 0.0
    with _LOCK:
        prev = _STEP_ANCHOR.get(source)
        _STEP_ANCHOR[source] = (now, eng_flops)
    if seconds is None:
        if prev is None:
            return
        seconds = now - prev[0]
    if flops_per_step is None:
        flops = eng_flops - (prev[1] if prev else eng_flops)
    else:
        flops = flops_per_step * steps

    counter("mx_train_steps_total", "Completed training steps",
            ("source",)).labels(source).inc(steps)
    counter("mx_train_examples_total", "Examples consumed by training",
            ("source",)).labels(source).inc(examples)
    histogram("mx_train_step_seconds", "Wall time per training step",
              ("source",)).labels(source).observe(seconds / max(steps, 1))
    # the SLO-ladder twin of mx_train_step_seconds: same documented
    # DEFAULT_LATENCY_BUCKETS exposition as serving, so training p50/p99
    # step latency is a real histogram_quantile() query too. Recorded at
    # the same window-admission pace (completion-paced, sync-free).
    host = _host_label()
    if host:
        histogram("mx_step_seconds",
                  "Training-step latency on the documented "
                  "DEFAULT_LATENCY_BUCKETS ladder",
                  ("source", "host"), buckets=DEFAULT_LATENCY_BUCKETS) \
            .labels(source, host).observe(seconds / max(steps, 1))
    else:
        histogram("mx_step_seconds",
                  "Training-step latency on the documented "
                  "DEFAULT_LATENCY_BUCKETS ladder",
                  ("source",), buckets=DEFAULT_LATENCY_BUCKETS) \
            .labels(source).observe(seconds / max(steps, 1))
    _trace_tick(steps)
    if tracing._ENABLED:
        # feed the anomaly watchdog the per-step seconds this function just
        # computed — host-side values only, no extra sync or clock read
        tracing.watch_step_time(seconds / max(steps, 1), source=source)
    if seconds > 0:
        gauge("mx_train_examples_per_second",
              "Training throughput over the last recorded window",
              ("source",)).labels(source).set(examples / seconds)
    if flops > 0:
        counter("mx_flops_total",
                "Estimated FLOPs executed (cost_analysis accounting)",
                ("source",)).labels(source).inc(flops)
        if seconds > 0:
            fps = flops / seconds
            gauge("mx_model_flops_per_second",
                  "Estimated achieved FLOP/s", ("source",)).labels(source) \
                .set(fps)
            gauge("mx_mfu",
                  "Estimated model FLOPs utilization vs peak_flops() "
                  "(see docs/observability.md for CPU caveats)",
                  ("source",)).labels(source).set(fps / peak_flops())
    if lr is not None:
        gauge("mx_learning_rate", "Optimizer learning rate",
              ("source",)).labels(source).set(lr)
    if goodput._ENABLED:
        # the goodput waterfall rides THIS funnel: one flag check while
        # disarmed, and armed attribution consumes only cumulative stamps
        # the layers already took (no extra syncs or clock reads)
        goodput._on_step(source, seconds, steps,
                         dispatch_wait=dispatch_wait_seconds)
    sample_memory()


def set_epoch(epoch: int, source: str = "module"):
    gauge("mx_epoch", "Current training epoch", ("source",)) \
        .labels(source).set(epoch)


@contextmanager
def timed(phase: str, source: str = ""):
    """Time a coarse phase (fit/eval/export) into mx_phase_seconds."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        histogram("mx_phase_seconds", "Coarse phase wall time",
                  ("phase", "source"),
                  buckets=tuple(1e-3 * (4 ** i) for i in range(10))) \
            .labels(phase, source).observe(time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# Collective-comms accounting
# ---------------------------------------------------------------------------

_tls = threading.local()


def payload_bytes(x) -> int:
    """Bytes in an NDArray / raw array / (nested) list/tuple of them."""
    if x is None:
        return 0
    if isinstance(x, (list, tuple)):
        return sum(payload_bytes(v) for v in x)
    size = getattr(x, "size", None)
    dtype = getattr(x, "dtype", None)
    if size is None or dtype is None:
        return 0
    try:
        import numpy as _np
        return int(size) * _np.dtype(str(dtype)).itemsize
    except Exception:
        return int(size) * 4


def record_comm(op: str, nbytes: int, store: str = "",
                seconds: Optional[float] = None, calls: int = 1,
                overlapped: bool = False, axis: str = ""):
    """Account one collective/comm operation (bytes moved, calls, time).

    `op` labels the collective kind — "allreduce", "reduce_scatter",
    "all_gather", the pipeline schedule's "ppermute" activation hops and
    "pipeline_grad_psum", "tp_weight_all_gather", the compute-partitioned
    TP path's "tp_act_psum"/"tp_act_all_gather"/"tp_act_psum_scatter",
    kvstore "push"/"pull" — so per-kind wire accounting survives
    aggregation (the check_instrumentation gate pins the trainer paths
    that must book here). `overlapped` marks traffic issued while backward
    compute was still pending (the chunked-vjp schedule,
    parallel/overlap.py); it becomes the "overlap" label and feeds the
    mx_comm_overlap_ratio gauge. `axis` names the MESH axis the collective
    crosses ("dp"/"tp"/"sp"/"pp"/"ep") so the ratio and byte totals split
    per parallelism lane — the signal that distinguishes "the dp grad
    allreduce overlaps fine" from "the tp weight gather is the
    unoverlapped remainder". Family.get(op, store) aggregates over the
    trailing labels, so two-label readers see totals unchanged. On a
    multi-process job the process rank rides as a trailing "host" label
    (same prefix-aggregation contract; comm_overlap_ratio and
    comm_axis_bytes index lv[2]/lv[3] positionally and are unaffected)."""
    ov = "1" if overlapped else "0"
    h = _host_label()
    names = ("op", "store", "overlap", "axis", "host") if h \
        else ("op", "store", "overlap", "axis")
    vals = (op, store, ov, axis, h) if h else (op, store, ov, axis)
    counter("mx_comm_bytes_total", "Bytes moved by comm/collective ops",
            names).labels(*vals).inc(max(int(nbytes), 0))
    counter("mx_comm_calls_total", "Comm/collective operations",
            names).labels(*vals).inc(calls)
    if seconds is not None:
        counter("mx_comm_seconds_total", "Wall seconds inside comm ops",
                names).labels(*vals).inc(seconds)


# gradient/weight-collective kinds eligible for backward overlap — the
# ratio denominator (kvstore push/pull and the pipeline's ppermute hops
# have no "issue during backward" notion and would only dilute the
# signal). The weight-sharded TP gather and the compute-partitioned
# activation collectives count: both are per-step wire traffic a schedule
# could in principle hide, and their per-axis remainder is the
# weight-sharded-vs-partitioned acceptance signal.
_OVERLAP_OPS = frozenset({
    "allreduce", "reduce_scatter", "all_gather", "tp_weight_all_gather",
    "tp_act_psum", "tp_act_all_gather", "tp_act_psum_scatter"})


def comm_overlap_ratio(axis: Optional[str] = None) -> float:
    """Fraction of gradient-collective wire traffic issued overlapped with
    backward compute. Byte-weighted over mx_comm_bytes_total's
    _OVERLAP_OPS series; since estimated collective seconds are
    bytes / peak_bytes_per_second() (the roofline interval accounting's
    conversion), the same number reads as the estimated-collective-time
    overlap fraction. `axis` restricts the accounting to one mesh axis's
    lane ("dp"/"tp"/"sp"/...): comm_overlap_ratio(axis="tp") == 0 with a
    zero byte total means the tp lane moved nothing unoverlapped — how the
    partitioned-TP tests assert the full-weight gather is gone. 0.0 when
    nothing has been recorded."""
    fam = get_metric("mx_comm_bytes_total")
    if fam is None:
        return 0.0
    with _LOCK:
        series = list(fam._series.items())
    total = overlapped = 0.0
    for lv, s in series:
        if not lv or lv[0] not in _OVERLAP_OPS:
            continue
        if axis is not None and (len(lv) < 4 or lv[3] != axis):
            continue
        v = getattr(s, "value", 0.0)
        total += v
        if len(lv) > 2 and lv[2] == "1":
            overlapped += v
    return overlapped / total if total else 0.0


def comm_axis_bytes(axis: str, overlapped: Optional[bool] = None) -> float:
    """Total mx_comm_bytes_total booked on one mesh axis's lane, optionally
    filtered to (non-)overlapped traffic. The partitioned-TP acceptance
    check reads comm_axis_bytes("tp") A/B between the weight-sharded and
    partitioned steps."""
    fam = get_metric("mx_comm_bytes_total")
    if fam is None:
        return 0.0
    with _LOCK:
        series = list(fam._series.items())
    total = 0.0
    for lv, s in series:
        if len(lv) < 4 or lv[3] != axis:
            continue
        if overlapped is not None and (lv[2] == "1") != overlapped:
            continue
        total += getattr(s, "value", 0.0)
    return total


def record_optimizer_state(nbytes: int, source: str = "trainer"):
    """Per-replica optimizer-state footprint gauge. The replicated update
    reports the full state; the ZeRO-style sharded update
    (DataParallelTrainer(zero_update=True)) reports ~1/dp of it — the
    memory-side acceptance signal of arXiv:2004.13336."""
    gauge("mx_optimizer_state_per_replica_bytes",
          "Optimizer-state bytes held per replica",
          ("source",)).labels(source).set(int(nbytes))


# ---------------------------------------------------------------------------
# Input-pipeline / dispatch-overlap instrumentation (engine/async_feed)
# ---------------------------------------------------------------------------

def record_feed_depth(depth: int, source: str = "feed"):
    """Batches currently staged on device by a DeviceFeed. A depth pinned
    at 0 while the device is busy means the producer keeps up exactly; a
    full queue means H2D is fully hidden behind compute."""
    gauge("mx_feed_queue_depth",
          "Device-resident batches staged ahead by the async feed",
          ("source",)).labels(source).set(int(depth))


def record_feed_stall(total_seconds: float, source: str = "feed"):
    """Cumulative consumer time spent waiting on an empty feed queue.
    Rendered as a counter (monotone per feed instance): nonzero growth
    means the input pipeline, not the device, bounds throughput."""
    gauge("mx_feed_stall_seconds_total",
          "Cumulative seconds the consumer stalled on an empty feed queue",
          ("source",)).labels(source).set(float(total_seconds))


def record_inflight(n: int, source: str = "step"):
    """Dispatched-but-incomplete training steps in a DispatchWindow."""
    gauge("mx_inflight_steps",
          "Training steps dispatched but not yet retired by the bounded "
          "in-flight window", ("source",)).labels(source).set(int(n))


def record_dispatch_wait(total_seconds: float, source: str = "step"):
    """Cumulative seconds a DispatchWindow blocked in admit()/drain()
    waiting on in-flight step completion (``window.wait_seconds``, a host
    float the window already accumulated — set-style like
    record_feed_stall). The goodput ledger's dispatch_backpressure
    category deltas this family when the trainer doesn't hand its window
    wait down through record_step directly."""
    gauge("mx_dispatch_wait_seconds_total",
          "Cumulative seconds the bounded in-flight window blocked on "
          "step completion", ("source",)).labels(source) \
        .set(total_seconds)


# ---------------------------------------------------------------------------
# Elastic fault tolerance (mxnet_tpu/elastic — docs/checkpointing.md)
# ---------------------------------------------------------------------------

def record_checkpoint_save(seconds: float, nbytes: int,
                           source: str = "elastic"):
    """Booked by the snapshot writer ON COMMIT (the background thread,
    never the step path): wall time from save() dispatch to manifest
    commit, and payload bytes this process wrote. save_seconds trending
    toward the snapshot interval means cadence outruns write bandwidth —
    the tuning signal docs/checkpointing.md's cadence section reads."""
    h = _host_label()
    if h:
        gauge("mx_checkpoint_save_seconds",
              "Wall seconds of the last snapshot, dispatch to manifest "
              "commit", ("source", "host")).labels(source, h) \
            .set(float(seconds))
    else:
        gauge("mx_checkpoint_save_seconds",
              "Wall seconds of the last snapshot, dispatch to manifest "
              "commit", ("source",)).labels(source).set(float(seconds))
    # the cumulative twin the goodput waterfall deltas into its
    # "snapshot" category (the last-save gauge above can't be deltaed)
    counter("mx_checkpoint_save_seconds_total",
            "Cumulative snapshot wall seconds written by this process",
            ("source",)).labels(source).inc(max(float(seconds), 0.0))
    counter("mx_checkpoint_bytes_total",
            "Cumulative snapshot payload bytes written by this process",
            ("source",)).labels(source).inc(int(nbytes))


def record_resume(outcome: str, source: str = "elastic"):
    """Boot-path outcome counter: ``fresh`` (no snapshot found),
    ``resumed`` (same mesh + step program), ``resharded`` (state was
    re-laid-out onto a different mesh). A fleet restarting after a
    preemption should show resumed/resharded, never fresh — fresh after
    a kill means snapshots are not landing."""
    counter("mx_resume_total",
            "Worker boots by restore outcome",
            ("outcome", "source")).labels(outcome, source).inc()


# ---------------------------------------------------------------------------
# MoE recipes (mxnet_tpu/recipes/moe.py — docs/large_models.md)
# ---------------------------------------------------------------------------

def record_moe_dropped(n: int, source: str = "moe"):
    """Capacity-overflow (token, choice) assignments dropped by top-k
    gating, summed over experts and devices. Booked at drain()/sync()
    from device handles the step path accumulated — never per step, so
    the counter costs no host sync on the hot path. A sustained rate
    above a few percent of tokens/step means capacity_factor is too low
    or the router collapsed (check it against the aux loss — see
    docs/large_models.md)."""
    counter("mx_moe_dropped_tokens_total",
            "Tokens dropped by MoE capacity overflow",
            ("source",)).labels(source).inc(max(int(n), 0))


# ---------------------------------------------------------------------------
# Serving SLO instrumentation (mxnet_tpu/serving — docs/serving.md)
# ---------------------------------------------------------------------------

# The documented default request-latency ladder: 1 ms .. 10 s, roughly
# log-spaced, so the cumulative `_bucket` exposition supports real
# histogram_quantile() p50/p99 queries for interactive inference. The
# serving layer records END-TO-END latency (enqueue -> result ready on
# host) into this ladder; pass ``buckets=`` to ``histogram()`` for a
# different SLO range.
DEFAULT_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                           0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def record_serving_enqueue(model: str, rows: int = 1):
    """Account one request admitted to a model's serving queue."""
    counter("mx_serving_requests_total", "Inference requests enqueued",
            ("model",)).labels(model).inc()
    counter("mx_serving_request_rows_total",
            "Rows (examples) across enqueued inference requests",
            ("model",)).labels(model).inc(max(int(rows), 0))


def record_serving_queue_depth(model: str, depth: int):
    """Requests waiting in the continuous batcher (set on every enqueue and
    every batch take, so scrapes see the live depth)."""
    gauge("mx_serving_queue_depth",
          "Requests waiting in the serving queue",
          ("model",)).labels(model).set(int(depth))


def record_serving_queue_wait(model: str, seconds: float):
    """Account one request's queue wait (enqueue -> batch take), the
    queueing share of mx_serving_request_seconds. Same SLO ladder, and
    derived from the two timestamps the batcher already stamps (t_enqueue,
    the take-time perf_counter read) — no new clock reads on the hot path.
    request_seconds p99 high while queue_wait p99 is low means the device,
    not admission, is the bottleneck; both high means queueing."""
    histogram("mx_serving_queue_wait_seconds",
              "Request queue wait (enqueue to batch take)",
              ("model",), buckets=DEFAULT_LATENCY_BUCKETS) \
        .labels(model).observe(float(seconds))


def record_serving_dispatch(model: str, bucket: int, rows: int):
    """Account one padded batch handed to the compiled per-bucket artifact:
    occupancy (real vs padded rows) is the batch-formation efficiency
    signal the bucket-set tuning loop reads (docs/serving.md)."""
    bucket = max(int(bucket), 1)
    rows = max(int(rows), 0)
    counter("mx_serving_batches_total", "Batches dispatched to the device",
            ("model", "bucket")).labels(model, str(bucket)).inc()
    counter("mx_serving_batch_rows_total",
            "Real (non-padding) rows dispatched",
            ("model", "bucket")).labels(model, str(bucket)).inc(rows)
    counter("mx_serving_padded_rows_total",
            "Padding rows dispatched (bucket size minus real rows)",
            ("model", "bucket")).labels(model, str(bucket)) \
        .inc(max(bucket - rows, 0))
    gauge("mx_serving_batch_occupancy",
          "Real-row fraction of the last dispatched bucket",
          ("model", "bucket")).labels(model, str(bucket)) \
        .set(rows / bucket)


def record_serving_completion(model: str, seconds: float, rows: int = 1,
                              status: str = "ok"):
    """Account one completed request: end-to-end latency (enqueue ->
    result on host) into the DEFAULT_LATENCY_BUCKETS histogram — p50/p99
    derive from the cumulative `_bucket` lines — plus response/row
    counters (per-model throughput = rate(mx_serving_response_rows_total))."""
    histogram("mx_serving_request_seconds",
              "End-to-end request latency (enqueue to result on host)",
              ("model",), buckets=DEFAULT_LATENCY_BUCKETS) \
        .labels(model).observe(float(seconds))
    counter("mx_serving_responses_total", "Completed inference requests",
            ("model", "status")).labels(model, status).inc()
    counter("mx_serving_response_rows_total",
            "Rows returned across completed requests",
            ("model",)).labels(model).inc(max(int(rows), 0))


# ---------------------------------------------------------------------------
# Reliability plane (mxnet_tpu/faults + hardened paths — docs/reliability.md)
# ---------------------------------------------------------------------------

def record_fault_injected(point: str):
    """Account one fault fired by the deterministic injection plane. In a
    chaos run this is the denominator every recovery metric divides by:
    mx_io_retries_total/mx_faults_injected_total ≈ 1 means every injected
    IO fault was absorbed by a retry."""
    counter("mx_faults_injected_total",
            "Faults fired by the injection plane (mxnet_tpu.faults)",
            ("point",)).labels(point).inc()


def record_io_retry(point: str):
    """Account one transient-IO retry (backoff+jitter) at a named fault
    point. A nonzero steady-state rate without armed chaos means the
    snapshot filesystem is genuinely flaky — page before it exhausts
    MXNET_TPU_IO_RETRIES and surfaces as failed snapshots."""
    counter("mx_io_retries_total",
            "Transient IO failures retried with exponential backoff",
            ("point",)).labels(point).inc()


def record_request_shed(model: str, reason: str = "queue_full"):
    """Account one serving request rejected or abandoned by admission
    control: ``queue_full`` (max_queue bound, HTTP 503), ``deadline``
    (expired while queued, HTTP 504), ``cancelled`` (caller timed out and
    reclaimed the queue slot). Shed rate vs mx_serving_requests_total is
    the overload signal the autoscaler should act on."""
    counter("mx_requests_shed_total",
            "Serving requests shed by admission control or deadlines",
            ("model", "reason")).labels(model, reason).inc()


def record_feed_producer_leak(source: str = "feed"):
    """Account one DeviceFeed producer thread abandoned after the join
    timeout (blocked inside the wrapped source). Each leak pins a thread
    until the source unblocks — a growing counter means the source needs
    an interruptible read or a larger MXNET_TPU_FEED_JOIN_TIMEOUT."""
    counter("mx_feed_producer_leaks_total",
            "DeviceFeed producer threads abandoned after join timeout",
            ("source",)).labels(source).inc()


def record_feed_producer_restart(source: str = "feed"):
    """Account one bounded DeviceFeed producer restart after a transient
    source error (supervised feed, MXNET_TPU_FEED_RESTARTS)."""
    counter("mx_feed_producer_restarts_total",
            "Bounded DeviceFeed producer restarts on transient errors",
            ("source",)).labels(source).inc()


def record_hosts_live(n: int, generation: int, source: str = "elastic"):
    """Multi-host control-plane group view (elastic/coordinator.py):
    hosts with a fresh membership lease, and the monotonic generation
    epoch. mx_hosts_live below the fleet size pages a dead host;
    mx_coordinator_generation climbing without deploys means hosts are
    flapping (lease expiry + rejoin) — check heartbeat IO latency."""
    gauge("mx_hosts_live",
          "Hosts with a fresh coordinator membership lease",
          ("source",)).labels(source).set(int(n))
    gauge("mx_coordinator_generation",
          "Monotonic group-membership generation epoch",
          ("source",)).labels(source).set(int(generation))


def record_commit_barrier(seconds: float, source: str = "elastic"):
    """Account one host's wait in the two-phase cross-host snapshot
    commit barrier (its own ready marker posted -> global manifest
    visible). p99 approaching the straggler deadline means one host's
    shard writes are outliers — the next incident is a straggler abort
    (mx_snapshot_failures_total{source="straggler"})."""
    histogram("mx_commit_barrier_seconds",
              "Cross-host snapshot commit barrier wait per host",
              ("source",), buckets=DEFAULT_LATENCY_BUCKETS) \
        .labels(source).observe(float(seconds))


def record_hang_watchdog(what: str):
    """Account one hang-watchdog firing (elastic/coordinator.py
    HangWatchdog): a wall-clock deadline expired on a blocking section
    (``drain``, ``commit``, ``heartbeat`` staleness). The process dumps
    the flight recorder and exits with a diagnosis — any increment is an
    incident; the NDJSON dump next to the job is the evidence."""
    counter("mx_hang_watchdog_fires_total",
            "Hang-watchdog firings (flight recorder dumped, process exited)",
            ("what",)).labels(what).inc()


@contextmanager
def comm_scope(op: str, nbytes: int, store: str = ""):
    """Time + count a comm region and annotate it into the device trace
    (jax.profiler.TraceAnnotation -> visible in xplane/TensorBoard).
    Re-entrant: nested scopes (pushpull -> push -> pull) count once."""
    if getattr(_tls, "in_comm", False):
        yield
        return
    _tls.in_comm = True
    ann = None
    try:
        import jax
        ann = jax.profiler.TraceAnnotation(f"mx.comm.{op}")
        ann.__enter__()
    except Exception:
        ann = None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        if ann is not None:
            ann.__exit__(None, None, None)
        _tls.in_comm = False
        record_comm(op, nbytes, store, seconds=t1 - t0)
        try:
            from .. import profiler as _profiler
            _profiler._record(op, "comm", t0, t1)
        except Exception:
            pass


def annotate(name: str):
    """Device-trace region (jax.profiler.TraceAnnotation) when telemetry is
    enabled — shows up inside the xplane timeline; nullcontext otherwise."""
    if not _ENABLED:
        return contextlib.nullcontext()
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


def instrument_comm(op: str):
    """Decorator for kvstore-style entry points `fn(self, key, value, ...)`:
    bytes-moved + timing + trace annotation when telemetry is enabled, one
    wrapper call + module-flag check when disabled."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kw):
            if not _ENABLED:
                return fn(self, *args, **kw)
            # args[0] is the key; the payload is the value/out argument
            payload = args[1] if len(args) > 1 \
                else kw.get("value", kw.get("out"))
            nbytes = payload_bytes(payload) or payload_bytes(kw.get("out"))
            with comm_scope(op, nbytes, getattr(self, "type", "")):
                return fn(self, *args, **kw)
        return wrapper
    return deco


# ---------------------------------------------------------------------------
# Memory watermarks
# ---------------------------------------------------------------------------

_mem_peak = 0.0


def sample_memory():
    """Sample live device-buffer bytes (jax.live_arrays) into
    mx_device_live_bytes / mx_device_peak_bytes. Called per recorded step;
    no-op when the runtime can't enumerate arrays."""
    global _mem_peak
    try:
        import jax
        live = float(sum(a.nbytes for a in jax.live_arrays()))
    except Exception:
        return
    with _LOCK:  # max() is a read-modify-write; _LOCK is reentrant
        _mem_peak = max(_mem_peak, live)
        peak = _mem_peak
    gauge("mx_device_live_bytes",
          "Live device-buffer bytes at the last sample").set(live)
    gauge("mx_device_peak_bytes",
          "Peak sampled device-buffer bytes").set(peak)


# ---------------------------------------------------------------------------
# Export
# ---------------------------------------------------------------------------

def _sync_engine_stats():
    """Mirror the compilation-engine counters (and donation savings) into
    gauges at scrape time, so one scrape carries the whole picture; the
    per-region roofline ledger refreshes its gauges here too."""
    from . import roofline as _roofline
    _roofline.export_metrics()
    if get_metric("mx_comm_bytes_total") is not None:
        gauge("mx_comm_overlap_ratio",
              "Fraction of gradient-collective wire bytes (equivalently, "
              "estimated collective seconds at the roofline bandwidth "
              "peak) issued overlapped with backward compute") \
            .set(comm_overlap_ratio())
        # per-mesh-axis split of the same ratio: the tp lane going to ~0
        # bytes (weight gather removed) vs staying a large unoverlapped
        # remainder is the weight-sharded vs compute-partitioned signal
        fam = get_metric("mx_comm_bytes_total")
        with _LOCK:
            axes = sorted({lv[3] for lv in fam._series
                           if len(lv) > 3 and lv[3]})
        for ax in axes:
            gauge("mx_comm_overlap_ratio_axis",
                  "Per-mesh-axis fraction of collective wire bytes issued "
                  "overlapped with backward compute",
                  ("axis",)).labels(ax).set(comm_overlap_ratio(axis=ax))
    try:
        from .. import engine as _engine
        st = _engine.cache_stats()
    except Exception:
        return
    for k, v in st.items():
        if isinstance(v, (int, float)):
            gauge(f"mx_compilation_{k}",
                  "Compilation-engine counter (engine.cache_stats)").set(v)
    total_dropped = sum(f.dropped for f in _FAMILIES.values())
    if total_dropped:
        gauge("mx_telemetry_dropped_series_total",
              "Series dropped by the per-family cardinality cap") \
            .set(total_dropped)


def collect() -> Dict[str, Any]:
    _sync_engine_stats()
    with _LOCK:
        fams = list(_FAMILIES.items())
    return {name: fam._as_dict() for name, fam in fams}


def scrape() -> str:
    """Prometheus text exposition of every registered metric, including the
    compilation-cache counters mirrored from engine.cache_stats()."""
    _sync_engine_stats()
    lines: List[str] = []
    with _LOCK:
        fams = list(_FAMILIES.values())
    for fam in fams:
        fam._render(lines)
    return "\n".join(lines) + "\n"


def scrape_json(indent=None) -> str:
    return json.dumps(collect(), indent=indent, sort_keys=True)


def report(reset_profiler: bool = False) -> str:
    """Human-readable status: telemetry summary + the profiler aggregate
    table + compilation stats — the unified `mx.telemetry.report()` view."""
    from .. import profiler as _profiler
    lines = ["=== telemetry ==="]
    for name, d in sorted(collect().items()):
        for s in d["series"]:
            lab = ",".join(f"{k}={v}" for k, v in s["labels"].items() if v)
            key = f"{name}{{{lab}}}" if lab else name
            if d["type"] == "histogram":
                cnt = s["count"]
                avg = s["sum"] / cnt if cnt else 0.0
                lines.append(f"{key:<56}count={cnt:<10}avg={avg:.6g}")
            else:
                lines.append(f"{key:<56}{s['value']:.6g}")
    lines.append("")
    lines.append("=== compilation (engine.cache_stats) ===")
    lines.append(json.dumps(_profiler.compilation_stats(), sort_keys=True,
                            default=str))
    lines.append("")
    lines.append("=== profiler aggregate stats ===")
    lines.append(_profiler.dumps(reset=reset_profiler))
    return "\n".join(lines)


def _family_snapshot(name: str) -> Dict[str, float]:
    """{joined-label-values: value} for one family (statusz rendering)."""
    fam = get_metric(name)
    if fam is None:
        return {}
    with _LOCK:
        series = list(fam._series.items())
    return {",".join(lv) or "_": getattr(s, "value", getattr(s, "sum", 0.0))
            for lv, s in series}


def statusz(extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The /statusz debug snapshot: config fingerprints (every declared
    MXNET_* knob whose live value differs from its default), compilation-
    cache stats, fault-plane arming, queue depth / in-flight gauges,
    anomaly counts, and the trailing flight-recorder entries. Served by
    both start_http_server() and serving.Server.start_http(); ``extra``
    merges caller-side sections (the serving server adds its model list)."""
    config = {}
    for name, (default, _typ, _doc) in sorted(env.items().items()):
        live = env.get(name)
        if live != default:
            config[name] = live
    try:
        from .. import engine as _engine
        compilation = {k: v for k, v in _engine.cache_stats().items()
                       if isinstance(v, (int, float, str))}
    except Exception:
        compilation = {}
    try:
        from .. import faults as _faults
        fault_plane = {"active": bool(_faults._ACTIVE),
                       "armed": _faults.armed()}
    except Exception:
        fault_plane = {}
    # group view only when the control plane is actually in use — the
    # import must not drag the coordinator in on single-host jobs
    coordinator: Dict[str, Any] = {}
    _coord_mod = sys.modules.get("mxnet_tpu.elastic.coordinator")
    if _coord_mod is not None:
        try:
            coordinator = _coord_mod.statusz_view()
        except Exception:
            coordinator = {}
    d: Dict[str, Any] = {
        "telemetry_enabled": _ENABLED,
        "tracing_enabled": tracing._ENABLED,
        "device_trace_active": trace_active(),
        "config": config,
        "compilation": compilation,
        "faults": fault_plane,
        "serving_queue_depth": _family_snapshot("mx_serving_queue_depth"),
        "inflight_steps": _family_snapshot("mx_inflight_steps"),
        "anomalies": _family_snapshot("mx_anomalies_total"),
        # compiled-HLO hazard audit (engine/hlo_audit.py): per-{kind,region}
        # hazard counts for every artifact built this process — the same
        # series Prometheus scrapes as mx_hlo_hazards_total
        "hlo_audit": _family_snapshot("mx_hlo_hazards_total"),
        "recorder_events": tracing.recent(),
        "coordinator": coordinator,
        "goodput": goodput.statusz_view(),
    }
    if extra:
        d.update(extra)
    return d


# ---------------------------------------------------------------------------
# HTTP /metrics endpoint (Prometheus scrape target)
# ---------------------------------------------------------------------------

_http_server = [None]


def start_http_server(port: int = 0, addr: str = "127.0.0.1") -> int:
    """Serve GET /metrics (Prometheus text), /metrics.json, /statusz, and
    /healthz on a daemon thread; returns the bound port (port=0 picks a
    free one)."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.startswith("/metrics.json"):
                body = scrape_json().encode()
                ctype = "application/json"
            elif self.path.startswith("/metrics"):
                body = scrape().encode()
                ctype = "text/plain; version=0.0.4"
            elif self.path.startswith("/statusz"):
                body = json.dumps(statusz(), default=str).encode()
                ctype = "application/json"
            elif self.path.startswith("/healthz"):
                body = b'{"status": "ok"}'
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer((addr, port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="mx-telemetry-http")
    t.start()
    with _LOCK:
        _http_server[0] = srv
    return srv.server_address[1]


def stop_http_server():
    with _LOCK:
        srv, _http_server[0] = _http_server[0], None
    if srv is not None:
        srv.shutdown()
        srv.server_close()


# the per-region roofline ledger (mx.telemetry.roofline.report() / rows();
# imported last — it only pulls stdlib at module scope)
from . import roofline  # noqa: E402
# the span-tracing plane + flight recorder (same stdlib-only constraint;
# record_step and statusz() above reference it at call time)
from . import tracing  # noqa: E402
# the goodput waterfall ledger (stdlib-only at module scope; record_step
# and statusz() above reference it at call time)
from . import goodput  # noqa: E402
