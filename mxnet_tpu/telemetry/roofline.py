"""Per-region roofline ledger: attribute achieved-vs-peak FLOPs AND bytes
to every compiled artifact (fused region) in the process.

The aggregate ``mx_mfu`` gauge says *that* half the chip is idle; this
ledger says *where*. Every compiled artifact the engine executes — gluon
cached graphs (fwd and the compiled vjp pullback separately), the fused
``DataParallelTrainer``/``PipelineTrainer`` steps, Predictor/serving
forwards — reports into one table keyed on the artifact's fingerprint
("region"), carrying the XLA cost model's FLOPs and ``bytes accessed``
captured once at artifact-build time (``engine.estimate_cost``).

Analysis frame ("Operator Fusion in XLA", arXiv:2301.13062): each region is
placed on the roofline by its arithmetic intensity ``AI = flops / bytes``
against the ridge point ``peak_flops / peak_bytes_per_second`` —
compute-bound above the ridge, memory-bound below — and its *attainable*
ceiling is ``min(peak_flops, AI * peak_bw)``. The headline ranking metric
is **lost FLOP-seconds** = ``ceiling * seconds - flops``: how much compute
the region left on the table relative to what the roofline says its own
shape could sustain. This per-region compute/memory classification is the
input signal a TVM-style cost-model-driven schedule search (arXiv:
1802.04799) consumes.

Timing is **completion-paced and sync-free**: each recorded execution is
stamped with the host wall-interval since the *previous* recorded
execution event (a process-global anchor), the same interval convention as
``telemetry.record_step``. Under the bounded in-flight window
(``DispatchWindow`` backpressure) dispatch pace equals completion pace, so
intervals sum to wall time and each interval is attributed to the artifact
that retired in it — no ``block_until_ready``, no host sync, ever
(enforced by the mxlint ``host-sync``/``sync-in-loop`` hot lists).

Exports, all OFF until telemetry is enabled:

- Prometheus — ``mx_region_achieved_flops_ratio{region,kind}``,
  ``mx_region_bytes_per_second{region,kind}`` (+ flops/s, arithmetic
  intensity, lost-FLOP-seconds, executions) refreshed at every scrape;
- ``report()`` — human table sorted by lost FLOP-seconds (worst first);
- ``as_dict()`` / ``dump_json()`` — machine-readable dump for bench
  (``BENCH_SCENARIO=roofline`` writes it into BENCHMARKS.md).
"""
from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "record", "register_cost", "rows", "report", "as_dict", "dump_json",
    "reset", "classify", "wrap", "total_flops",
]

# region key -> _Region; guarded by telemetry's registry lock (the ledger
# is part of the same process-wide registry lifecycle: reset() clears both)
_LEDGER: "Dict[str, _Region]" = {}
# perf_counter stamp of the last recorded execution event (process-global):
# interval pacing attributes inter-completion gaps to the retiring region
_ANCHOR: List[Optional[float]] = [None]


def _lock():
    from . import _LOCK
    return _LOCK


class _Region:
    """One ledger row: cumulative FLOPs/bytes/seconds for one artifact."""

    __slots__ = ("name", "kind", "execs", "flops", "bytes", "seconds",
                 "estimated", "cost")

    def __init__(self, name: str, kind: str = ""):
        self.name = name
        self.kind = kind
        self.execs = 0
        self.flops = 0.0
        self.bytes = 0.0
        self.seconds = 0.0
        # True while any contribution used a heuristic cost (e.g. the
        # gluon "bwd = 2x fwd" fallback) rather than a captured one
        self.estimated = False
        self.cost: Dict[str, float] = {}


def register_cost(region: str, cost: Dict[str, float], kind: str = ""):
    """Attach the artifact's build-time cost detail (estimate_cost output:
    flops / bytes_accessed / bytes_in / bytes_out / peak_memory_bytes /
    transcendentals) to its ledger row without booking an execution."""
    with _lock():
        row = _LEDGER.get(region)
        if row is None:
            row = _LEDGER[region] = _Region(region, kind)
        if cost:
            row.cost = dict(cost)
        if kind:
            row.kind = kind


def record(region: str, flops: float = 0.0, bytes_accessed: float = 0.0,
           steps: int = 1, kind: str = "", seconds: Optional[float] = None,
           estimated: bool = False, cost: Optional[Dict[str, float]] = None):
    """Book ``steps`` executions of ``region`` covering ``flops``/``bytes``
    total. ``seconds=None`` uses interval pacing against the global anchor
    (the first event only anchors the clock); an explicit ``seconds`` also
    re-anchors, so mixed callers stay consistent. Arguments must be host
    scalars (cost-model floats) — this path is on the mxlint host-sync hot
    list precisely so no device value can ever sneak in."""
    now = time.perf_counter()
    with _lock():
        row = _LEDGER.get(region)
        if row is None:
            row = _LEDGER[region] = _Region(region, kind)
        elif kind and not row.kind:
            row.kind = kind
        if cost:
            row.cost = dict(cost)
        row.execs += steps
        row.flops += flops
        row.bytes += bytes_accessed
        row.estimated = row.estimated or estimated
        prev, _ANCHOR[0] = _ANCHOR[0], now
        if seconds is None:
            seconds = (now - prev) if prev is not None else 0.0
        row.seconds += seconds


def total_flops() -> float:
    """Sum of FLOPs across every ledger row — must agree with the engine's
    aggregate ``flops_executed`` counter (both are fed by the same
    ``engine.record_execution`` funnel; BENCH_SCENARIO=roofline asserts
    the two accounts within 5%)."""
    with _lock():
        return sum(r.flops for r in _LEDGER.values())


def reset():
    with _lock():
        _LEDGER.clear()
        _ANCHOR[0] = None


# ---------------------------------------------------------------------------
# Derived roofline placement
# ---------------------------------------------------------------------------

def classify(flops: float, bytes_accessed: float) -> str:
    """'compute' when the region's arithmetic intensity sits at/above the
    ridge point (peak_flops / peak_bytes_per_second), 'memory' below it,
    'unknown' without a bytes figure."""
    from . import peak_bytes_per_second, peak_flops
    if bytes_accessed <= 0:
        return "unknown"
    ridge = peak_flops() / peak_bytes_per_second()
    return "compute" if flops / bytes_accessed >= ridge else "memory"


def rows() -> List[Dict[str, Any]]:
    """Ledger rows with derived roofline fields, sorted by lost
    FLOP-seconds (the attribution ranking: worst waste first)."""
    from . import peak_bytes_per_second, peak_flops
    pf, pb = peak_flops(), peak_bytes_per_second()
    with _lock():
        snap = [(r.name, r.kind, r.execs, r.flops, r.bytes, r.seconds,
                 r.estimated, dict(r.cost)) for r in _LEDGER.values()]
    out = []
    for name, kind, execs, flops, nbytes, secs, est, cost in snap:
        ai = flops / nbytes if nbytes > 0 else float("inf") if flops else 0.0
        ceiling = min(pf, ai * pb) if nbytes > 0 else pf
        fps = flops / secs if secs > 0 else 0.0
        bps = nbytes / secs if secs > 0 else 0.0
        out.append({
            "region": name,
            "kind": kind,
            "executions": execs,
            "flops": flops,
            "bytes": nbytes,
            "seconds": secs,
            "achieved_flops_per_second": fps,
            "achieved_bytes_per_second": bps,
            "achieved_flops_ratio": fps / pf if pf else 0.0,
            "achieved_bytes_ratio": bps / pb if pb else 0.0,
            "arithmetic_intensity": ai,
            "bound": classify(flops, nbytes),
            "roofline_ceiling_flops_per_second": ceiling,
            "lost_flop_seconds": max(ceiling * secs - flops, 0.0),
            "estimated": est,
            "cost": cost,
        })
    out.sort(key=lambda r: r["lost_flop_seconds"], reverse=True)
    return out


def as_dict() -> Dict[str, Any]:
    from . import peak_bytes_per_second, peak_flops
    pf, pb = peak_flops(), peak_bytes_per_second()
    return {
        "peak_flops_per_second": pf,
        "peak_bytes_per_second": pb,
        "ridge_point_flops_per_byte": pf / pb if pb else 0.0,
        "total_flops": total_flops(),
        "regions": rows(),
    }


def dump_json(path: Optional[str] = None, indent=None) -> str:
    """JSON dump of the ledger (bench/BENCHMARKS.md vehicle); writes to
    ``path`` when given, returns the text either way."""
    text = json.dumps(as_dict(), indent=indent, sort_keys=True)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def report() -> str:
    """Human table sorted by lost FLOP-seconds: the action list for "where
    is the MFU going" (docs/observability.md, "Reading the ledger")."""
    d = as_dict()
    lines = [
        "=== roofline ledger (peak %.3g FLOP/s, %.3g B/s, ridge %.1f "
        "FLOP/B) ===" % (d["peak_flops_per_second"],
                         d["peak_bytes_per_second"],
                         d["ridge_point_flops_per_byte"]),
        f"{'region':<44}{'kind':<6}{'execs':>6}{'GFLOP':>9}{'GB':>8}"
        f"{'sec':>8}{'fl/s%':>7}{'B/s%':>6}{'AI':>8} {'bound':<8}"
        f"{'lostFLOPs':>10}",
    ]
    for r in d["regions"]:
        est = "~" if r["estimated"] else ""
        lines.append(
            f"{est + r['region']:<44}{r['kind']:<6}{r['executions']:>6}"
            f"{r['flops'] / 1e9:>9.2f}{r['bytes'] / 1e9:>8.2f}"
            f"{r['seconds']:>8.3f}"
            f"{100 * r['achieved_flops_ratio']:>7.2f}"
            f"{100 * r['achieved_bytes_ratio']:>6.1f}"
            f"{r['arithmetic_intensity']:>8.1f} {r['bound']:<8}"
            f"{r['lost_flop_seconds'] / 1e9:>10.2f}")
    lines.append("('~' prefix = row contains heuristic-estimated costs; "
                 "lostFLOPs = GFLOP-seconds below the region's own "
                 "roofline ceiling)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Prometheus export (refreshed per scrape by telemetry._sync_engine_stats)
# ---------------------------------------------------------------------------

def export_metrics():
    """Mirror the ledger into labeled gauges. Label cardinality is bounded
    by the artifact count (itself bounded by the compilation cache), far
    under the per-family series cap."""
    from . import gauge
    for r in rows():
        lab = (r["region"], r["kind"])
        gauge("mx_region_achieved_flops_ratio",
              "Per-region achieved FLOP/s over peak_flops() "
              "(docs/observability.md, roofline ledger)",
              ("region", "kind")).labels(*lab).set(r["achieved_flops_ratio"])
        gauge("mx_region_bytes_per_second",
              "Per-region achieved memory bandwidth",
              ("region", "kind")).labels(*lab) \
            .set(r["achieved_bytes_per_second"])
        gauge("mx_region_flops_per_second",
              "Per-region achieved FLOP/s",
              ("region", "kind")).labels(*lab) \
            .set(r["achieved_flops_per_second"])
        gauge("mx_region_arithmetic_intensity",
              "Per-region FLOPs per byte accessed (vs the ridge point)",
              ("region", "kind")).labels(*lab).set(
                  r["arithmetic_intensity"]
                  if r["arithmetic_intensity"] != float("inf") else 0.0)
        gauge("mx_region_lost_flop_seconds",
              "FLOPs the region left below its own roofline ceiling",
              ("region", "kind")).labels(*lab).set(r["lost_flop_seconds"])
        gauge("mx_region_executions",
              "Recorded executions of the region's compiled artifact",
              ("region", "kind")).labels(*lab).set(r["executions"])


# ---------------------------------------------------------------------------
# Instrumenting ad-hoc jitted callables (bench / user kernels)
# ---------------------------------------------------------------------------

def wrap(jitted, region: str, kind: str = "custom") -> Callable:
    """Instrument a jitted callable as a ledger region: the first call
    (while telemetry is enabled) captures its cost via
    ``engine.estimate_cost``, and every call books one execution through
    the same ``engine.record_execution`` funnel the framework artifacts
    use — so wrapped kernels land in the same table AND the same aggregate
    ``flops_executed`` account."""
    from .. import engine as _engine
    from . import is_enabled
    state = {"cost": None}

    def call(*args, **kw):
        if is_enabled() and state["cost"] is None:
            state["cost"] = _engine.estimate_cost(jitted, *args, kind=kind)
        out = jitted(*args, **kw)
        c = state["cost"] or {}
        _engine.record_execution(kind, c.get("flops", 0.0),
                                 bytes_accessed=c.get("bytes_accessed", 0.0),
                                 region=region, cost=c)
        return out

    call.__name__ = f"roofline[{region}]"
    return call
