"""``mx.npx`` — NumPy-extension namespace (reference
python/mxnet/numpy_extension/ + npx ops in src/operator/numpy/).

Neural-net ops with NumPy-style arrays: thin re-dispatch to the registered
op set, returning mx.np.ndarray so the two namespaces compose.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, invoke
from ..ops.registry import get_op
from ..util import set_np, reset_np, is_np_shape, is_np_array, use_np
from ..numpy import ndarray as np_ndarray, _wrap, _apply


def _npx_op(op_name, *arrays, **params):
    ins = [a if isinstance(a, NDArray) else np_ndarray(jnp.asarray(a))
           for a in arrays if a is not None]
    out = invoke(get_op(op_name), ins, params)
    if isinstance(out, list):
        if len(out) == 1:
            return _renp(out[0])
        return [_renp(o) for o in out]
    return _renp(out)


def _renp(x: NDArray) -> np_ndarray:
    out = np_ndarray(x._data, x._ctx)
    out._ag_node = x._ag_node
    return out


def softmax(data, axis=-1, length=None, temperature=None):
    return _npx_op("softmax", data, length, axis=axis, temperature=temperature,
                   use_length=length is not None)


def log_softmax(data, axis=-1):
    return _npx_op("log_softmax", data, axis=axis)


def relu(data):
    return _npx_op("relu", data)


def sigmoid(data):
    return _npx_op("sigmoid", data)


def gelu(data):
    return _apply(jax.nn.gelu, (data,), {})


def leaky_relu(data, slope=0.25):
    return _npx_op("LeakyReLU", data, act_type="leaky", slope=slope)


def activation(data, act_type="relu"):
    return _npx_op("Activation", data, act_type=act_type)


def batch_dot(a, b, transpose_a=False, transpose_b=False):
    return _npx_op("batch_dot", a, b, transpose_a=transpose_a,
                   transpose_b=transpose_b)


def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    return _npx_op("FullyConnected", data, weight, bias,
                   num_hidden=num_hidden, no_bias=no_bias or bias is None,
                   flatten=flatten)


def convolution(data, weight, bias=None, **params):
    return _npx_op("Convolution", data, weight, bias,
                   no_bias=bias is None, **params)


def pooling(data, **params):
    return _npx_op("Pooling", data, **params)


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-3,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               output_mean_var=False, axis=1):
    return _npx_op("BatchNorm", x, gamma, beta, running_mean, running_var,
                   eps=eps, momentum=momentum, fix_gamma=fix_gamma,
                   use_global_stats=use_global_stats,
                   output_mean_var=output_mean_var, axis=axis)


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    return _npx_op("LayerNorm", data, gamma, beta, axis=axis, eps=eps)


def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False):
    return _npx_op("Embedding", data, weight, input_dim=input_dim,
                   output_dim=output_dim)


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False):
    return _npx_op("topk", data, axis=axis, k=k, ret_typ=ret_typ,
                   is_ascend=is_ascend)


def pick(data, index, axis=-1, mode="clip", keepdims=False):
    return _npx_op("pick", data, index, axis=axis, mode=mode, keepdims=keepdims)


def gather_nd(data, indices):
    return _npx_op("gather_nd", data, indices)


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _npx_op("one_hot", data, depth=depth, on_value=on_value,
                   off_value=off_value, dtype=dtype)


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    return _npx_op("SequenceMask", data, sequence_length,
                   use_sequence_length=use_sequence_length, value=value,
                   axis=axis)


def reshape_like(lhs, rhs):
    return _npx_op("reshape_like", lhs, rhs)


def _npx_reshape_infer(src, target):
    """_npx_reshape's own special-code table (reference
    src/operator/numpy/np_matrix_op.cc NumpyXReshapeInferShape): -1 infer,
    -2 copy one src dim, -3 skip a size-1 src dim, -4 copy ALL remaining
    src dims, -5 merge two src dims, -6 split one src dim in two (next two
    target entries, one may be -1). NOTE: different from legacy Reshape."""
    if all(d >= 0 for d in target):
        return tuple(target)
    out = []
    unknown = -1
    known_prod = 1
    si = 0
    i = 0
    while i < len(target):
        d = target[i]
        if d == -1:
            if unknown >= 0:
                raise ValueError("npx.reshape: only one dim can be inferred")
            unknown = len(out)
            out.append(-1)
            si += 1
        elif d == -2:
            out.append(src[si]); known_prod *= src[si]; si += 1
        elif d == -3:
            if src[si] != 1:
                raise ValueError("npx.reshape: -3 requires a size-1 dim")
            si += 1
        elif d == -4:
            while si < len(src):
                out.append(src[si]); known_prod *= src[si]; si += 1
        elif d == -5:
            m = src[si] * src[si + 1]
            out.append(m); known_prod *= m; si += 2
        elif d == -6:
            d0 = src[si]; si += 1
            d1, d2 = target[i + 1], target[i + 2]
            i += 2
            if d1 == -1 and d2 == -1:
                raise ValueError("npx.reshape: split dims cannot both be -1")
            if d1 == -1:
                d1 = d0 // d2
            elif d2 == -1:
                d2 = d0 // d1
            if d1 * d2 != d0:
                raise ValueError("npx.reshape: invalid -6 split")
            out.extend([d1, d2]); known_prod *= d0
        elif d > 0:
            out.append(d); known_prod *= d; si += 1
        else:
            raise ValueError(f"npx.reshape: invalid dim {d}")
        i += 1
    total = 1
    for s in src:
        total *= s
    if unknown >= 0:
        out[unknown] = total // known_prod
    return tuple(out)


def reshape(a, newshape, reverse=False, order="C"):
    """`npx.reshape` (reference _npx_reshape, np_matrix_op.cc:198) with its
    special codes; reverse=True matches dims right-to-left."""
    if isinstance(newshape, int):
        newshape = (newshape,)
    src = tuple(a.shape)
    tgt = tuple(int(d) for d in newshape)
    if reverse:
        shape = tuple(reversed(_npx_reshape_infer(src[::-1], tgt[::-1])))
    else:
        shape = _npx_reshape_infer(src, tgt)
    return _npx_op("Reshape", a, shape=shape)


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    r = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    n = r.size if axis is None else r.shape[axis]
    n_base = -(-n // repeat) if repeat > 1 else n
    out = jnp.arange(start, start + step * n_base, step, dtype=jnp.float32)
    if repeat > 1:
        out = jnp.repeat(out, repeat)[:n]
    return _wrap(out)


def smooth_l1(data, scalar=1.0):
    return _npx_op("smooth_l1", data, scalar=scalar)


def erf(data):
    return _apply(jax.scipy.special.erf, (data,), {})


def erfinv(data):
    return _apply(jax.scipy.special.erfinv, (data,), {})


def gamma(data):
    return _apply(lambda x: jnp.exp(jax.scipy.special.gammaln(x)), (data,), {})


def gammaln(data):
    return _apply(jax.scipy.special.gammaln, (data,), {})


def seed(s):
    from .. import random as _rng
    _rng.seed(s)


def waitall():
    from ..ndarray import waitall as _w
    _w()


def cpu(i=0):
    from ..context import cpu as _cpu
    return _cpu(i)


def gpu(i=0):
    from ..context import gpu as _gpu
    return _gpu(i)


def num_gpus():
    from ..context import num_gpus as _n
    return _n()


def current_device():
    from ..context import current_context
    return current_context()


def load(fname):
    from ..serialization import load_ndarrays
    return load_ndarrays(fname)


def save(fname, data):
    from ..serialization import save_ndarrays
    save_ndarrays(fname, data)


def bernoulli(prob=None, logit=None, size=None, dtype=None, ctx=None,
              out=None):
    """Bernoulli samples parameterized by prob OR logit, not both
    (reference numpy_extension/random.py:78)."""
    from .. import random as _rng
    if (prob is None) == (logit is None):
        raise MXNetError("bernoulli needs exactly one of prob/logit")
    p = prob if prob is not None else jax.nn.sigmoid(
        jnp.asarray(getattr(logit, "_data", logit)))
    p = jnp.asarray(getattr(p, "_data", p))
    shape = p.shape if size is None else (
        (size,) if isinstance(size, int) else tuple(size))
    draw = jax.random.bernoulli(_rng.next_key(), p, shape) \
        .astype(jnp.dtype(dtype) if dtype else jnp.float32)
    if ctx is not None:
        draw = jax.device_put(draw, ctx.jax_device)
    res = _renp(NDArray(draw))
    if out is not None:
        out._set_data(draw)
        return out
    return res


def _batched_draw(base, params, batch_shape, dtype, ctx):
    """Shared body of the *_n samplers: draw batch_shape + broadcast
    params.shape and apply the affine transform; honors ctx placement."""
    from .. import random as _rng
    arrs = [jnp.asarray(getattr(pv, "_data", pv), jnp.float32)
            for pv in params]
    pshape = jnp.broadcast_shapes(*(a.shape for a in arrs))
    batch = () if batch_shape is None else (
        (batch_shape,) if isinstance(batch_shape, int) else
        tuple(batch_shape))
    raw = base(_rng.next_key(), batch + pshape,
               jnp.dtype(dtype) if dtype else jnp.float32, arrs)
    if ctx is not None:
        raw = jax.device_put(raw, ctx.jax_device)
    return _renp(NDArray(raw))


def uniform_n(low=0.0, high=1.0, batch_shape=None, dtype=None, ctx=None):
    """Like np.random.uniform but `batch_shape` is PREPENDED to the
    broadcast parameter shape (reference numpy_extension/random.py:131
    uniform_n: out.shape = batch_shape + params.shape)."""
    def base(key, shape, dt, ps):
        lo, hi = ps
        u = jax.random.uniform(key, shape, dt)
        return (lo + (hi - lo) * u).astype(dt)
    return _batched_draw(base, (low, high), batch_shape, dtype, ctx)


def normal_n(loc=0.0, scale=1.0, batch_shape=None, dtype=None, ctx=None):
    """Like np.random.normal but `batch_shape` is PREPENDED (reference
    numpy_extension/random.py normal_n)."""
    def base(key, shape, dt, ps):
        m, sd = ps
        return (m + sd * jax.random.normal(key, shape, dt)).astype(dt)
    return _batched_draw(base, (loc, scale), batch_shape, dtype, ctx)
