"""Base utilities: errors, env-var config registry, dtype tables.

TPU-native re-design of the reference's dmlc foundations:
  - MXNetError            <- reference include/mxnet/base.h (dmlc::Error)
  - environment knobs     <- reference docs .../env_var.md (dmlc::GetEnv call sites)
  - dtype name table      <- reference include/mxnet/base.h / mshadow type switch

No code is shared with the reference; this is a typed Python config registry
(SURVEY.md section 5-f recommends mapping env vars to a typed registry).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

import numpy as _np


class MXNetError(RuntimeError):
    """Error raised by the framework (name kept for API parity)."""


class NotSupportedForSparseNDArray(MXNetError):
    pass


# ---------------------------------------------------------------------------
# Typed environment/config registry (replacement for dmlc::GetEnv sprawl)
# ---------------------------------------------------------------------------

class _EnvRegistry:
    """Typed registry over MXNET_* environment variables.

    Every knob the framework reads is declared here so `mxnet_tpu.runtime`
    can enumerate them (the reference documents 85 MXNET_* env vars; we keep
    the same discoverability with actual typing).
    """

    def __init__(self) -> None:
        self._decls: Dict[str, tuple] = {}
        self._lock = threading.Lock()

    def declare(self, name: str, default: Any, typ: Callable[[str], Any], doc: str = "") -> None:
        with self._lock:
            self._decls.setdefault(name, (default, typ, doc))

    def get(self, name: str, default: Any = None, typ: Optional[Callable] = None) -> Any:
        if name in self._decls:
            ddefault, dtyp, _ = self._decls[name]
            default = default if default is not None else ddefault
            typ = typ or dtyp
        raw = os.environ.get(name)
        if raw is None:
            return default
        if typ is bool:
            return raw.lower() in ("1", "true", "yes", "on")
        return (typ or str)(raw)

    def items(self):
        return dict(self._decls)


env = _EnvRegistry()
env.declare("MXNET_ENGINE_TYPE", "Async", str, "Async (jax dispatch) or Naive (sync after every op)")
env.declare("MXNET_ENFORCE_DETERMINISM", False, bool, "Force deterministic reductions")
env.declare("MXNET_DEFAULT_DTYPE", "float32", str, "Default dtype for new arrays")
env.declare("MXNET_SAFE_ACCUMULATION", True, bool, "Accumulate reductions in float32 even for bf16 inputs")
env.declare("MXNET_PROFILER_AUTOSTART", False, bool, "Start profiler at import")
env.declare("MXNET_EXEC_BULK_EXEC_TRAIN", True, bool, "Kept for API parity; XLA always fuses")


# ---------------------------------------------------------------------------
# dtype tables (mirrors mshadow type codes for serialization parity)
# ---------------------------------------------------------------------------

# Codes follow the reference's mshadow/base.h enum so .params files and
# serialized attrs stay interoperable in spirit.
_DTYPE_TO_CODE = {
    _np.dtype("float32"): 0,
    _np.dtype("float64"): 1,
    _np.dtype("float16"): 2,
    _np.dtype("uint8"): 3,
    _np.dtype("int32"): 4,
    _np.dtype("int8"): 5,
    _np.dtype("int64"): 6,
    _np.dtype("bool"): 7,
    # TPU-native addition: bfloat16 is the workhorse dtype on the MXU.
    "bfloat16": 8,
}
_CODE_TO_DTYPE = {v: k for k, v in _DTYPE_TO_CODE.items()}


def dtype_code(dtype) -> int:
    import jax.numpy as jnp
    d = jnp.dtype(dtype)
    if d == jnp.bfloat16:
        return _DTYPE_TO_CODE["bfloat16"]
    return _DTYPE_TO_CODE[_np.dtype(str(d))]


def code_dtype(code: int):
    import jax.numpy as jnp
    d = _CODE_TO_DTYPE[code]
    return jnp.bfloat16 if d == "bfloat16" else jnp.dtype(d)


def default_dtype():
    import jax.numpy as jnp
    return jnp.dtype(env.get("MXNET_DEFAULT_DTYPE"))


_GRAD_REQ_MAP = {"null": 0, "write": 1, "add": 3}


def string_types():
    return (str,)
