"""Runtime kernel compilation (reference include/mxnet/rtc.h:39 CudaModule /
python/mxnet/rtc.py over NVRTC).

TPU analog: NVRTC-compiled CUDA strings become runtime-compiled Pallas
kernels. PallasModule accepts either a Python kernel function (refs in,
writes out) or a SOURCE STRING of Python/Pallas code compiled at runtime —
the direct counterpart of mx.rtc.CudaModule(source).get_kernel(...).launch:

    src = '''
    def axpy(x_ref, y_ref, o_ref):
        o_ref[...] = 2.0 * x_ref[...] + y_ref[...]
    '''
    mod = mx.rtc.PallasModule(src)
    kern = mod.get_kernel("axpy", out_shapes=[((64, 64), "float32")])
    (z,) = kern.launch([x, y])

Off-TPU, kernels run through the Pallas interpreter (same code path tests
use); grid/block geometry maps to the Pallas grid.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple, Union

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray


class PallasKernel:
    """A launchable kernel (reference rtc.py CudaKernel)."""

    def __init__(self, fn: Callable, name: str, out_shapes, grid=None,
                 interpret: Optional[bool] = None):
        self._fn = fn
        self.name = name
        self._out_shapes = out_shapes
        self._grid = grid
        self._interpret = interpret

    def launch(self, args: Sequence, ctx=None, grid=None,
               interpret: Optional[bool] = None):
        """Run the kernel. args: NDArrays/arrays; returns tuple of NDArrays
        (reference launch(args, ctx, grid_dims, block_dims) — block dims are
        a CUDA notion; the Pallas grid subsumes both)."""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        raws = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                for a in args]
        interp = interpret if interpret is not None else self._interpret
        if interp is None:
            from .ops.pallas.flash_attention import _on_tpu
            interp = not (raws and _on_tpu(raws[0]))
        out_shape = [jax.ShapeDtypeStruct(tuple(s), jnp.dtype(dt))
                     for s, dt in self._out_shapes]
        grid = grid or self._grid
        kw = {"grid": grid} if grid is not None else {}
        call = pl.pallas_call(
            self._fn,
            out_shape=out_shape if len(out_shape) > 1 else out_shape[0],
            interpret=bool(interp),
            **kw,
        )
        outs = call(*raws)
        outs = outs if isinstance(outs, (list, tuple)) else (outs,)
        return tuple(NDArray(o) for o in outs)


class PallasModule:
    """Runtime-compiled kernel module (reference rtc.py CudaModule).

    source: a Python source string defining one or more Pallas kernel
    functions, or a single callable. exports lists the kernel names
    (defaults to every top-level function in the source).
    """

    def __init__(self, source: Union[str, Callable], options=(), exports=()):
        self._kernels: dict = {}
        if callable(source):
            self._kernels[source.__name__] = source
        else:
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu
            import textwrap
            namespace = {"jax": jax, "jnp": jnp, "pl": pl, "pltpu": pltpu,
                         "np": _np}
            code = textwrap.dedent(source)
            exec(compile(code, "<mx.rtc.PallasModule>", "exec"), namespace)
            import types
            for name, obj in list(namespace.items()):
                if isinstance(obj, types.FunctionType) and \
                        obj.__code__.co_filename == "<mx.rtc.PallasModule>":
                    self._kernels[name] = obj
        if exports:
            missing = [e for e in exports if e not in self._kernels]
            if missing:
                raise MXNetError(f"exports not found in source: {missing}")

    def get_kernel(self, name: str, signature: str = "", *, out_shapes,
                   grid=None, interpret: Optional[bool] = None) -> PallasKernel:
        """signature is accepted for API parity and ignored (Pallas kernels
        are shape-polymorphic until launch)."""
        if name not in self._kernels:
            raise MXNetError(
                f"kernel '{name}' not found; available: "
                f"{sorted(self._kernels)}")
        return PallasKernel(self._kernels[name], name, out_shapes, grid,
                            interpret)


# Reference-name alias: mx.rtc.CudaModule(source) keeps old call sites alive
CudaModule = PallasModule
