"""Runtime feature detection (reference src/libinfo.cc, python/mxnet/runtime.py)."""
from __future__ import annotations

from collections import namedtuple

import jax

Feature = namedtuple("Feature", ["name", "enabled"])


def _detect():
    plats = {d.platform for d in jax.devices()}
    feats = {
        "TPU": any(p != "cpu" for p in plats),
        "CPU": True,
        "XLA": True,
        "PALLAS": True,
        "BF16": True,
        "INT64_TENSOR_SIZE": True,
        "SHARDING": True,
        "DIST_KVSTORE": True,
        "PROFILER": True,
        "TELEMETRY": True,
        "OPENMP": False,
        "CUDA": False,
        "CUDNN": False,
        "MKLDNN": False,
        "TENSORRT": False,
        "OPENCV": _has("cv2"),
        "SIGNAL_HANDLER": True,
    }
    return {k: Feature(k, v) for k, v in feats.items()}


def _has(mod):
    import importlib.util
    return importlib.util.find_spec(mod) is not None


class Features(dict):
    def __init__(self):
        super().__init__(_detect())

    def is_enabled(self, name: str) -> bool:
        f = self.get(name.upper())
        return bool(f and f.enabled)

    def __repr__(self):
        return "[" + ", ".join(("✔" if f.enabled else "✖") + " " + f.name
                               for f in self.values()) + "]"


def feature_list():
    return list(Features().values())
