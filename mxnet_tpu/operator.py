"""Custom operator API (reference python/mxnet/operator.py +
src/operator/custom/custom-inl.h:52-192).

User subclasses CustomOp (forward/backward with self.assign) and
CustomOpProp (shapes/types/create_operator), registers with
@mx.operator.register("name"), then calls mx.nd.Custom(..., op_type="name").

TPU-native notes: custom ops run EAGERLY on the host (the reference runs
them on dedicated worker threads outside the engine for the same reason —
arbitrary Python can't live inside the compiled graph). Their outputs
re-enter the jax world as device arrays; autograd records a tape node whose
backward invokes the op's `backward`. Inside a jit trace, Custom raises —
wrap the call in `jax.pure_callback` manually if host execution inside a
compiled function is really wanted.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as _np

from .base import MXNetError

_CUSTOM_REGISTRY: Dict[str, type] = {}


class CustomOp:
    """(reference operator.py CustomOp)"""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError()

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError()

    def assign(self, dst, req, src):
        """(reference CustomOp.assign)"""
        from .ndarray import NDArray
        if req in ("null",):
            return
        src_nd = src if isinstance(src, NDArray) else NDArray(src)
        if req in ("write", "inplace", None):
            dst._set_data(src_nd._data.astype(dst.dtype))
        elif req == "add":
            dst._set_data((dst._data + src_nd._data).astype(dst.dtype))
        else:
            raise MXNetError(f"unknown req {req}")


class CustomOpProp:
    """(reference operator.py CustomOpProp)"""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError()


def register(reg_name: str):
    """Decorator registering a CustomOpProp subclass (reference
    operator.py register)."""
    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        return prop_cls
    return deco


def get_custom_prop(op_type: str, **kwargs) -> CustomOpProp:
    cls = _CUSTOM_REGISTRY.get(op_type)
    if cls is None:
        raise MXNetError(f"custom op '{op_type}' is not registered")
    return cls(**kwargs)


def custom(*inputs, op_type: str, **kwargs):
    """mx.nd.Custom — eager execution + tape recording
    (reference MXImperativeInvoke on the Custom op, custom-inl.h)."""
    import jax
    from .ndarray import NDArray
    from .context import current_context
    from . import autograd

    if any(isinstance(getattr(x, "_data", None), jax.core.Tracer)
           for x in inputs):
        raise MXNetError(
            "Custom ops run on the host and cannot be traced into a "
            "compiled graph; call outside jit/hybridize or wrap with "
            "jax.pure_callback")

    prop = get_custom_prop(op_type, **kwargs)
    in_nd = [x if isinstance(x, NDArray) else NDArray(x) for x in inputs]
    in_shapes = [list(x.shape) for x in in_nd]
    _, out_shapes, aux_shapes = prop.infer_shape(in_shapes)
    in_types = [x.dtype for x in in_nd]
    _, out_types, aux_types = prop.infer_type(in_types)

    from .ndarray import zeros
    out_nd = [zeros(tuple(s), dtype=str(_np.dtype(t)))
              for s, t in zip(out_shapes, out_types)]
    aux_nd = [zeros(tuple(s), dtype=str(_np.dtype(t)))
              for s, t in zip(aux_shapes, aux_types)]

    op = prop.create_operator(current_context(), in_shapes, in_types)
    is_train = autograd.is_training() if hasattr(autograd, "is_training") \
        else autograd.is_recording()
    op.forward(is_train=is_train, req=["write"] * len(out_nd),
               in_data=in_nd, out_data=out_nd, aux=aux_nd)

    if autograd.is_recording() and any(x._ag_node is not None for x in in_nd):
        fwd_in = list(in_nd)
        fwd_out = list(out_nd)

        def vjp_fn(cotangents):
            if not isinstance(cotangents, (list, tuple)):
                cotangents = (cotangents,)
            out_grad = [NDArray(g) for g in cotangents]
            in_grad = [zeros(x.shape, dtype=str(x.dtype)) for x in fwd_in]
            op.backward(req=["write"] * len(in_grad), out_grad=out_grad,
                        in_data=fwd_in, out_data=fwd_out, in_grad=in_grad,
                        aux=aux_nd)
            return tuple(g._data for g in in_grad)

        autograd.record_op(vjp_fn, in_nd, out_nd,
                           out_is_tuple=len(out_nd) > 1)
    if len(out_nd) == 1:
        return out_nd[0]
    return out_nd
