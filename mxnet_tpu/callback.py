"""Training callbacks (reference python/mxnet/callback.py).

do_checkpoint:55, Speedometer:245, log_train_metric, ProgressBar — consumed
by Module.fit / FeedForward exactly as in the reference.
"""
from __future__ import annotations

import logging
import math
import time


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Checkpoint a Module every `period` epochs (reference callback.py:27)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end checkpoint callback (reference callback.py:55)."""
    from .model import save_checkpoint
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def log_train_metric(period, auto_reset=False):
    """(reference callback.py:78)"""
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset_local()
    return _callback


class Speedometer:
    """samples/sec logger (reference callback.py:245)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.auto_reset = auto_reset

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                try:
                    speed = self.frequent * self.batch_size / \
                        (time.time() - self.tic)
                except ZeroDivisionError:
                    speed = float("inf")
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset_local()
                    msg = "Epoch[%d] Batch [%d-%d]\tSpeed: %.2f samples/sec"
                    msg += "\t%s=%f" * len(name_value)
                    logging.info(msg, param.epoch, count - self.frequent,
                                 count, speed,
                                 *sum(name_value, ()))
                else:
                    logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                                 param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """(reference callback.py:310)"""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")


class LogValidationMetricsCallback:
    """Log eval metrics at epoch end (reference callback.py:214)."""

    def __call__(self, param):
        if not getattr(param, "eval_metric", None):
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f",
                         param.epoch, name, value)


class TelemetryCallback:
    """Periodic telemetry exporter for the fit-loop hooks.

    Use the instance as a `batch_end_callback` (exports the running
    eval-metric values into `mx_train_metric` gauges and, every `frequent`
    batches, refreshes the scrape file / log) and its `.epoch_end` bound
    method as an `epoch_end_callback` / lr_scheduler epoch hook (sets
    `mx_epoch` and refreshes the export). The per-step metrics themselves
    (step time, examples/sec, MFU) come from the instrumented fit loops —
    this callback is the periodic EXPORT vehicle, so it never double-counts
    steps.

        mod.fit(it, num_epoch=2,
                batch_end_callback=cb, epoch_end_callback=cb.epoch_end)
    """

    def __init__(self, frequent=50, scrape_path=None, log_report=False,
                 enable=True):
        from . import telemetry
        self._telem = telemetry
        if enable:
            telemetry.enable()
        self.frequent = int(frequent)
        self.scrape_path = scrape_path
        self.log_report = log_report
        self._nbatch = 0

    def _export(self):
        if self.scrape_path:
            tmp = f"{self.scrape_path}.tmp"
            with open(tmp, "w") as f:
                f.write(self._telem.scrape())
            import os
            os.replace(tmp, self.scrape_path)
        if self.log_report:
            logging.info("telemetry:\n%s", self._telem.report())

    def __call__(self, param):
        t = self._telem
        if not t._ENABLED:
            return
        if getattr(param, "eval_metric", None) is not None:
            for name, value in param.eval_metric.get_name_value():
                if value == value:  # skip NaN (empty metric)
                    t.gauge("mx_train_metric", "Running training metric",
                            ("name",)).labels(name).set(value)
        self._nbatch += 1
        if self.frequent and self._nbatch % self.frequent == 0:
            self._export()

    def epoch_end(self, iter_no, sym=None, arg=None, aux=None):
        if self._telem._ENABLED:
            self._telem.set_epoch(iter_no + 1)
            self._export()
