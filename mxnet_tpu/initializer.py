"""Weight initializers (reference python/mxnet/initializer.py)."""
from __future__ import annotations

import math
import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as _np

from .base import MXNetError
from . import random as _rng

_INIT_REGISTRY = {}


def register(cls):
    _INIT_REGISTRY[cls.__name__.lower()] = cls
    return cls


class InitDesc(str):
    """Parameter name + attrs used to pick per-parameter behavior."""
    def __new__(cls, name, attrs=None, global_init=None):
        s = super().__new__(cls, name)
        s.attrs = attrs or {}
        s.global_init = global_init
        return s


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, desc, arr):
        # init values are computed host-side and would otherwise land on
        # jax's default device — pin the result back to the destination
        # array's device (a Module bound to mx.cpu() on a TPU-visible
        # process must keep its params on the CPU)
        dev = None
        data = getattr(arr, "_data", None)
        if data is not None:
            devs = data.devices()
            if len(devs) == 1:
                dev = next(iter(devs))
        self._dispatch(desc, arr)
        if dev is not None and arr._data.devices() != {dev}:
            arr._set_data(jax.device_put(arr._data, dev))

    def _dispatch(self, desc, arr):
        if not isinstance(desc, str):
            desc = InitDesc("weight")
        init_name = getattr(desc, "attrs", {}).get("__init__", None)
        if init_name:
            create(init_name)._init_impl(desc, arr)
            return
        name = str(desc).lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_one(desc, arr)
        elif name.endswith("beta"):
            self._init_zero(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        else:
            self._init_weight(desc, arr)

    def _init_impl(self, desc, arr):
        self.__call__(desc, arr)

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_bias(self, desc, arr):
        arr[:] = 0.0

    def _init_zero(self, desc, arr):
        arr[:] = 0.0

    def _init_one(self, desc, arr):
        arr[:] = 1.0

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 0.0


zeros = Zero


@register
class One(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 1.0


ones = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        raw = jax.random.uniform(_rng.next_key(), arr.shape, jnp.float32,
                                 -self.scale, self.scale)
        arr._set_data(raw.astype(arr.dtype))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        raw = self.sigma * jax.random.normal(_rng.next_key(), arr.shape, jnp.float32)
        arr._set_data(raw.astype(arr.dtype))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale

    def _init_weight(self, desc, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        raw = jax.random.orthogonal(_rng.next_key(), max(nout, nin))[:nout, :nin]
        arr._set_data((self.scale * raw).reshape(arr.shape).astype(arr.dtype))


def _fans(shape, factor_type="avg"):
    hw = int(_np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * hw if len(shape) > 1 else shape[0]
    fan_out = shape[0] * hw
    return fan_in, fan_out


@register
class Xavier(Initializer):
    """reference initializer.py Xavier (uniform/gaussian, avg/in/out)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        fan_in, fan_out = _fans(arr.shape)
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError(f"factor_type {self.factor_type}")
        scale = math.sqrt(self.magnitude / max(factor, 1))
        if self.rnd_type == "uniform":
            raw = jax.random.uniform(_rng.next_key(), arr.shape, jnp.float32, -scale, scale)
        else:
            raw = scale * jax.random.normal(_rng.next_key(), arr.shape, jnp.float32)
        arr._set_data(raw.astype(arr.dtype))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, desc, arr):
        weight = _np.zeros(arr.shape, dtype="float32")
        shape = arr.shape
        f = shape[3] // 2 + shape[3] % 2  # ceil
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set_data(jnp.asarray(weight, dtype=arr.dtype))


@register
class LSTMBias(Initializer):
    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        v = _np.zeros(arr.shape, dtype="float32")
        n = arr.shape[0] // 4
        v[n:2 * n] = self.forget_bias  # gate order i f g o
        arr._set_data(jnp.asarray(v, dtype=arr.dtype))

    _init_bias = _init_weight


_ALIASES = {"zeros": "zero", "ones": "one", "gaussian": "normal",
            "msraprelu": "msraprelu", "bilinear": "bilinear"}


def create(name, **kwargs) -> Initializer:
    if isinstance(name, Initializer):
        return name
    if isinstance(name, str):
        key = name.lower()
        key = _ALIASES.get(key, key)
        if key in _INIT_REGISTRY:
            return _INIT_REGISTRY[key](**kwargs)
        # mxnet serializes init as json ['xavier', {...}]
        import json
        try:
            spec = json.loads(name)
            return _INIT_REGISTRY[spec[0].lower()](**spec[1])
        except Exception:
            pass
    raise MXNetError(f"unknown initializer {name!r}")


class Load:
    """Initialize variables from a params file or dict (reference
    initializer.py:319). ``arg:``/``aux:`` prefixes are dropped; names not
    found fall back to ``default_init``."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .model import load_params
            arg, aux = load_params(param)
            param = {**arg, **aux}
        self.param = {}
        for name, arr in param.items():
            key = name[4:] if name.startswith(("arg:", "aux:")) else name
            self.param[key] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        key = str(name)
        if key in self.param:
            src = self.param[key]
            raw = src._data if hasattr(src, "_data") else jnp.asarray(src)
            if tuple(raw.shape) != tuple(arr.shape):
                raise MXNetError(
                    f"Load: parameter {key} shape mismatch "
                    f"{tuple(raw.shape)} vs {tuple(arr.shape)}")
            arr._set_data(raw.astype(arr.dtype))
            if self.verbose:
                import logging
                logging.info("Initialized %s by loading", key)
        else:
            if self.default_init is None:
                raise MXNetError(
                    f"Load: no initialization for {key} and no "
                    "default_init given")
            self.default_init(name, arr)


class Mixed:
    """Pattern-dispatched initializer list (reference initializer.py:366):
    the FIRST regex that matches the parameter name picks the
    initializer."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("Mixed: len(patterns) != len(initializers)")
        self.map = [(re.compile(p), i) for p, i in zip(patterns,
                                                       initializers)]

    def __call__(self, name, arr):
        key = str(name)
        for prog, init in self.map:
            if prog.match(key):
                init(name if isinstance(name, InitDesc) else InitDesc(key),
                     arr)
                return
        raise MXNetError(
            f"Mixed: parameter {key} did not match any pattern; add '.*' "
            "as the final pattern for a default")


@register
class FusedRNN(Initializer):
    """Initialize the fused RNN op's FLAT parameter vector (reference
    initializer.py:720): unpack per-layer/per-direction wx/wh/bx/bh slices
    (the layout of ops/nn.py _unpack_rnn_params), run the inner
    initializer on each, and set the LSTM forget-gate bias."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        super().__init__(init=None, num_hidden=num_hidden,
                         num_layers=num_layers, mode=mode,
                         bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = create(init) if isinstance(init, str) else init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from .ops.nn import _gates
        ng = _gates(self._mode)
        h = self._num_hidden
        L = self._num_layers
        d = 2 if self._bidirectional else 1
        total = int(arr.shape[0])
        # solve the flat length for input_size (layer 0 reads it; deeper
        # layers read h*d): total = d*ng*h*(isz + h)
        #   + (L-1)*d*ng*h*(h*d + h) + L*d*2*ng*h
        rest = (L - 1) * d * ng * h * (h * d + h) + L * d * 2 * ng * h \
            + d * ng * h * h
        isz = (total - rest) // (d * ng * h)
        if isz <= 0 or d * ng * h * (isz + h) + rest - d * ng * h * h \
                != total:
            raise MXNetError("FusedRNN: parameter length does not match "
                             "num_hidden/num_layers/mode")
        out = _np.empty(total, dtype=_np.float32)
        off = 0

        def fill(shape, kind):
            # bias-suffixed desc so the inner initializer's name dispatch
            # routes bias slices to _init_bias (zeros), matching the
            # reference's per-name unpack_weights initialization
            nonlocal off
            n = int(_np.prod(shape))
            tmp = _NDArrayShim(shape)
            self._init(InitDesc(f"{desc}_{kind}"), tmp)
            out[off:off + n] = _np.asarray(tmp._data).reshape(-1)
            off += n

        for layer in range(L):
            for _dir in range(d):
                cur = isz if layer == 0 else h * d
                fill((ng * h, cur), "weight")
                fill((ng * h, h), "weight")
        for layer in range(L):
            for _dir in range(d):
                for _b in range(2):   # bx, bh
                    start = off
                    fill((ng * h,), "bias")
                    if self._mode == "lstm":
                        # gate order [i f g o]: the reference writes
                        # forget_bias into EVERY *_f_bias (i2h AND h2h),
                        # so the cell's bx+bh sums to 2*forget_bias
                        out[start + h:start + 2 * h] = self._forget_bias
        arr._set_data(jnp.asarray(out, dtype=arr.dtype))


class _NDArrayShim:
    """Minimal array target for inner initializers (supports the
    _set_data / __setitem__ surface they use)."""

    def __init__(self, shape):
        self._data = jnp.zeros(shape, jnp.float32)
        self.shape = tuple(shape)
        self.dtype = jnp.float32

    def _set_data(self, raw):
        self._data = jnp.asarray(raw, jnp.float32).reshape(self.shape)

    def __setitem__(self, key, value):
        if key == slice(None):
            self._data = jnp.full(self.shape, float(value), jnp.float32) \
                if _np.isscalar(value) else \
                jnp.asarray(value, jnp.float32).reshape(self.shape)
        else:
            raise MXNetError("shim supports full-slice assignment only")
