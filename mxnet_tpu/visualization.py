"""Network visualization (reference python/mxnet/visualization.py):
print_summary (layer table with shapes/params) and plot_network (graphviz
when available, text tree otherwise)."""
from __future__ import annotations

from typing import Dict, Optional

import numpy as _np

from .base import MXNetError


def print_summary(symbol, shape: Optional[Dict] = None, line_length=120,
                  positions=(0.44, 0.64, 0.74, 1.0)):
    """Layer-by-layer summary (reference visualization.py:print_summary)."""
    shape_dict = {}
    if shape is not None:
        arg_shapes, out_shapes, _ = symbol.infer_shape_partial(**shape)
        arg_names = symbol.list_arguments()
        shape_dict = dict(zip(arg_names, arg_shapes or []))
    topo = symbol._topo()
    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(vals):
        line = ""
        for v, pos in zip(vals, positions):
            line = (line + str(v))[:pos - 1].ljust(pos)
        print(line)

    print("_" * line_length)
    print_row(fields)
    print("=" * line_length)
    total_params = 0
    # infer every node's output shape in one pass when input shapes given
    node_shapes = {}
    if shape is not None:
        import jax
        import numpy as np
        from .symbol.symbol import _resolved_params
        info = {}
        for node in topo:
            if node.kind == "var":
                s = shape_dict.get(node.name)
                info[id(node)] = [s]
                continue
            try:
                import jax.numpy as jnp
                structs = []
                ok = True
                for inp, oi in node.inputs:
                    cell = info.get(id(inp), [None])
                    s = cell[oi] if oi < len(cell) else None
                    if s is None:
                        ok = False
                        break
                    structs.append(jax.ShapeDtypeStruct(tuple(s), jnp.float32))
                if not ok:
                    info[id(node)] = [None]
                    continue
                out = jax.eval_shape(node.op.unbound(_resolved_params(node)),
                                     *structs)
                outs = out if isinstance(out, tuple) else (out,)
                info[id(node)] = [tuple(o.shape) for o in outs]
            except Exception:
                info[id(node)] = [None]
        node_shapes = info

    for node in topo:
        if node.kind == "var":
            continue
        out_shape = (node_shapes.get(id(node), [None]) or [None])[0]
        n_params = 0
        prevs = []
        for inp, _ in node.inputs:
            if inp.kind == "var" and inp.name not in shape_dict:
                pass
            if inp.kind == "var":
                s = shape_dict.get(inp.name)
                if s is not None and not inp.name.endswith(("data", "label")):
                    n_params += int(_np.prod(s))
            else:
                prevs.append(inp.name)
        total_params += n_params
        print_row([f"{node.name} ({node.op.name})",
                   out_shape if out_shape else "",
                   n_params, ", ".join(prevs)])
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz digraph when graphviz is installed; otherwise returns a
    text rendering of the DAG (reference visualization.py:plot_network)."""
    topo = symbol._topo()
    try:
        from graphviz import Digraph
    except ImportError:
        lines = []
        for node in topo:
            if node.kind == "var":
                if not hide_weights or not node.name.endswith(
                        ("weight", "bias", "gamma", "beta", "moving_mean",
                         "moving_var")):
                    lines.append(f"[var] {node.name}")
                continue
            ins = ", ".join(i.name for i, _ in node.inputs
                            if not (hide_weights and i.kind == "var"
                                    and i.name != "data"))
            lines.append(f"[{node.op.name}] {node.name} <- {ins}")
        return "\n".join(lines)

    dot = Digraph(name=title, format=save_format)
    for node in topo:
        if node.kind == "var":
            if hide_weights and node.name.endswith(
                    ("weight", "bias", "gamma", "beta", "moving_mean",
                     "moving_var")):
                continue
            dot.node(node.name, node.name, shape="oval")
        else:
            dot.node(node.name, f"{node.name}\n{node.op.name}", shape="box")
            for inp, _ in node.inputs:
                if hide_weights and inp.kind == "var" and inp.name != "data":
                    continue
                dot.edge(inp.name, node.name)
    return dot
