"""Continuous-batching inference serving on the compiled artifact cache.

The production serving path between a single ``predict.Predictor`` call and
millions-of-users traffic (ROADMAP item 3; the capability the reference
covers with c_predict_api + the model-server ecosystem, rebuilt TPU-native
around fixed-shape XLA artifacts, arXiv:1810.09868):

  - **ModelRegistry / RegisteredModel** (`registry.py`) — exported
    symbol+params load once; each configured batch bucket (e.g. 1/8/64)
    eagerly acquires a compiled artifact through the process-wide engine
    cache under pinned ``("predict", graph_fp, config_fingerprint)`` keys,
    warm-started from ``MXNET_TPU_COMPILATION_CACHE_DIR`` so a restarted
    replica does not recompile.
  - **ContinuousBatcher** (`batcher.py`) — thread-safe request queue with
    continuous batch formation: requests aggregate into the smallest
    covering bucket, padded rows are sliced back per request, a
    ``max_wait_ms`` deadline bounds p99, and a ``DispatchWindow`` keeps K
    batches in flight (explicit ``device_put`` feeding, no host sync on
    the dispatch path).
  - **Server** (`server.py`) — multi-model front door: in-process
    ``submit()/result()`` futures plus a stdlib HTTP JSON API and the
    Prometheus ``/metrics`` endpoint.

SLO observability rides the unified telemetry layer: request-latency
histograms on ``telemetry.DEFAULT_LATENCY_BUCKETS`` (p50/p99 from the
cumulative ``_bucket`` exposition), queue depth, batch occupancy, and
per-model throughput — see docs/serving.md and docs/observability.md.

Like ``mxnet_tpu.predict``, this package stays off the training stack: it
imports only the symbolic core, the engine, and telemetry.
"""
from __future__ import annotations

from .registry import ModelRegistry, RegisteredModel
from .batcher import (ContinuousBatcher, DeadlineExceeded, PRIORITIES,
                      ServerOverloaded, ServingFuture)
from .server import Server

__all__ = ["ModelRegistry", "RegisteredModel", "ContinuousBatcher",
           "ServingFuture", "Server", "ServerOverloaded",
           "DeadlineExceeded", "PRIORITIES"]
