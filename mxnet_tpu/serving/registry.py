"""Model registry: exported symbol+params -> per-bucket pinned artifacts.

A ``RegisteredModel`` loads one exported model (symbol-JSON + params, the
same files ``Predictor`` consumes) ONCE, places the parameters on device
(replicated over the mesh when one is given), and eagerly acquires one
compiled inference artifact per batch bucket through
``predict.acquire_forward`` — i.e. through the process-wide engine
compilation cache under ``("predict", graph_fp, config_fingerprint)`` keys.
Registration therefore IS the warmup: every bucket compiles (or loads from
``MXNET_TPU_COMPILATION_CACHE_DIR`` — restart != recompile) before the
first request arrives, and the steady-state serve path never compiles.
Entries are pinned for the model's lifetime; ``close()`` releases them.

Memory budgeting: parameters are held exactly once per model regardless of
bucket count (artifacts are parameter-free pure functions — params enter
as call inputs), so a registry's device footprint is
``sum(model.param_bytes)`` plus XLA's per-bucket executables.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError
from ..predict import ForwardArtifact, acquire_forward, load_params

__all__ = ["RegisteredModel", "ModelRegistry"]


class RegisteredModel:
    """One served model: shared params + one pinned artifact per bucket.

    ``input_shapes`` maps each graph data input to its PER-ROW shape (no
    batch dimension) — bucket ``B`` binds input ``(B, *row_shape)``. With
    ``mesh`` + ``data_spec`` the request batch is dp-sharded over the mesh
    (params replicated), the same explicit-``device_put`` placement rule as
    ``engine.DeviceFeed``; every bucket must then divide evenly over the
    sharded axis.
    """

    def __init__(self, name: str, symbol_file: str,
                 param_file: Optional[str] = None,
                 input_shapes: Optional[Dict[str, Sequence[int]]] = None,
                 buckets: Sequence[int] = (1, 8, 64),
                 dtype: str = "float32",
                 dtypes: Optional[Dict[str, str]] = None,
                 mesh=None, data_spec=None):
        from .. import faults as _faults
        from .. import symbol as sym_mod
        self.name = name
        # artifact loads ride the same transient-IO retry as elastic
        # snapshots (a registry boot on a flaky model store should not
        # need an operator retry loop)
        self._sym = _faults.io_retry("serving.load", sym_mod.load,
                                     symbol_file)
        self._dtype = dtype
        self._dtypes = dict(dtypes or {})
        self._mesh = mesh
        self._data_spec = data_spec
        self.buckets: Tuple[int, ...] = tuple(sorted(
            {int(b) for b in buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise MXNetError(f"buckets must be positive ints, got {buckets}")
        arg_params, aux_params = ({}, {}) if param_file is None \
            else _faults.io_retry("serving.load", load_params, param_file)
        self._arg_params = {k: self._place_param(self._raw(v))
                            for k, v in arg_params.items()}
        self._aux_params = {k: self._place_param(self._raw(v))
                            for k, v in aux_params.items()}
        self.input_names: List[str] = [
            n for n in self._sym.list_arguments() if n not in self._arg_params]
        self.output_names: List[str] = self._sym.list_outputs()
        if input_shapes is None:
            raise MXNetError(
                "RegisteredModel needs input_shapes: per-row shapes (no "
                f"batch dim) for the graph inputs {self.input_names}")
        missing = [n for n in self.input_names if n not in input_shapes]
        if missing:
            raise MXNetError(
                f"input_shapes missing {missing}; the graph's data inputs "
                f"are {self.input_names}")
        self._row_shapes = {k: tuple(int(s) for s in v)
                            for k, v in input_shapes.items()}
        if self._mesh is not None:
            axis = self._batch_axis_size()
            bad = [b for b in self.buckets if b % axis]
            if bad:
                raise MXNetError(
                    f"buckets {bad} do not divide over the sharded batch "
                    f"axis (size {axis}) of mesh {dict(self._mesh.shape)}")
        self._arts: Dict[int, ForwardArtifact] = {}
        self._closed = False
        self._warm_all()

    # -- placement (the DeviceFeed explicit-device_put rule) -----------------
    @staticmethod
    def _raw(v):
        return getattr(v, "handle", getattr(v, "_data", v))

    def _batch_axis_size(self) -> int:
        from jax.sharding import PartitionSpec
        spec = self._data_spec if self._data_spec is not None \
            else PartitionSpec(*self._mesh.axis_names[:1])
        first = tuple(spec)[0] if tuple(spec) else None
        if first is None:
            return 1
        names = first if isinstance(first, tuple) else (first,)
        n = 1
        for a in names:
            n *= self._mesh.shape[a]
        return n

    def _place_param(self, raw):
        import jax
        if self._mesh is None:
            return jax.device_put(raw)
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.device_put(raw, NamedSharding(self._mesh,
                                                 PartitionSpec()))

    def place_input(self, name: str, raw):
        """Explicit ``device_put`` of one request tensor with the model's
        input placement (dp-sharded batch dim under a mesh) — the transfer
        the dispatch loop pays up front so the compiled call itself is
        transfer-free."""
        import jax
        if self._mesh is None:
            return jax.device_put(raw)
        from jax.sharding import NamedSharding, PartitionSpec
        spec = self._data_spec if self._data_spec is not None \
            else PartitionSpec(*self._mesh.axis_names[:1])
        ndim = getattr(raw, "ndim", len(self._row_shapes[name]) + 1)
        clipped = PartitionSpec(*tuple(spec)[:ndim])
        return jax.device_put(raw, NamedSharding(self._mesh, clipped))

    # -- signature helpers ---------------------------------------------------
    def input_dtype(self, name: str) -> str:
        return self._dtypes.get(name, self._dtype)

    def row_shape(self, name: str) -> Tuple[int, ...]:
        return self._row_shapes[name]

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def smallest_bucket(self, rows: int) -> int:
        """The smallest configured bucket covering ``rows`` (the padded
        batch the dispatch loop will run)."""
        for b in self.buckets:
            if b >= rows:
                return b
        raise MXNetError(
            f"{rows} rows exceed the largest bucket {self.max_bucket} of "
            f"model {self.name!r}")

    @property
    def param_bytes(self) -> int:
        """Device bytes held by this model's parameters (once per model —
        the multi-model memory-budgeting number in docs/serving.md)."""
        total = 0
        for v in list(self._arg_params.values()) \
                + list(self._aux_params.values()):
            total += int(getattr(v, "nbytes", 0) or 0)
        return total

    # -- artifacts -----------------------------------------------------------
    def _sharding_tag(self) -> str:
        if self._mesh is None:
            return ""
        spec = tuple(self._data_spec) if self._data_spec is not None \
            else tuple(self._mesh.axis_names[:1])
        return f"mesh={tuple(sorted(self._mesh.shape.items()))},spec={spec}"

    def _avals(self, bucket: int):
        arg_avals = {
            n: ((bucket,) + self._row_shapes[n], self.input_dtype(n))
            for n in self.input_names}
        for n, v in self._arg_params.items():
            arg_avals[n] = (tuple(v.shape), str(v.dtype))
        aux_avals = {n: (tuple(v.shape), str(v.dtype))
                     for n, v in self._aux_params.items()}
        return arg_avals, aux_avals

    def _warm_all(self):
        """Eager startup warmup: one acquire (compile or persistent-cache
        load) per bucket, so the first real request hits a ready
        executable."""
        inputs = set(self.input_names)

        def place(name, z):
            return self.place_input(name, z) if name in inputs \
                else self._place_param(z)

        for b in self.buckets:
            arg_avals, aux_avals = self._avals(b)
            self._arts[b] = acquire_forward(
                self._sym, arg_avals, aux_avals,
                sharding_tag=self._sharding_tag(), place=place)

    def forward(self, bucket: int, feed: Dict[str, Any]):
        """Dispatch one padded bucket batch on the compiled artifact.
        ``feed`` values must already be device-placed (``place_input``);
        returns the RAW output arrays — no host sync on this path."""
        art = self._arts[bucket]
        arg_vals = tuple(feed[n] if n in feed else self._arg_params[n]
                         for n in art.arg_names)
        aux_vals = tuple(self._aux_params[n] for n in art.aux_names)
        return art(arg_vals, aux_vals)

    def close(self):
        """Release every bucket artifact's pin."""
        if self._closed:
            return
        self._closed = True
        for art in self._arts.values():
            art.release()
        self._arts.clear()


class ModelRegistry:
    """Name -> RegisteredModel, with aggregate memory accounting."""

    def __init__(self):
        self._lock = threading.RLock()
        self._models: "OrderedDict[str, RegisteredModel]" = OrderedDict()

    def register(self, name: str, symbol_file: str,
                 param_file: Optional[str] = None, **kwargs
                 ) -> RegisteredModel:
        with self._lock:
            if name in self._models:
                raise MXNetError(f"model {name!r} already registered")
        model = RegisteredModel(name, symbol_file, param_file, **kwargs)
        with self._lock:
            self._models[name] = model
        return model

    def get(self, name: str) -> RegisteredModel:
        with self._lock:
            try:
                return self._models[name]
            except KeyError:
                raise MXNetError(
                    f"unknown model {name!r}; registered: "
                    f"{list(self._models)}") from None

    def names(self) -> List[str]:
        with self._lock:
            return list(self._models)

    def unregister(self, name: str):
        with self._lock:
            model = self._models.pop(name, None)
        if model is not None:
            model.close()

    def total_param_bytes(self) -> int:
        with self._lock:
            models = list(self._models.values())
        return sum(m.param_bytes for m in models)

    def close(self):
        with self._lock:
            models = list(self._models.values())
            self._models.clear()
        for m in models:
            m.close()
