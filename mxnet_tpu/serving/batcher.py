"""Continuous batcher: thread-safe request queue -> bucketed padded batches.

The TPU-native continuous/dynamic batching policy (ROADMAP item 3): XLA
artifacts are fixed-shape, so instead of arbitrary dynamic batches the
batcher aggregates in-flight requests into the SMALLEST covering bucket
from the model's configured bucket set (e.g. 1/8/64), pads the tail rows,
and slices real rows back per request at completion. Latency is bounded:
a batch dispatches as soon as (a) it fills the largest bucket, (b) the next
queued request can no longer fit, or (c) the OLDEST queued request has
waited ``max_wait_ms`` — the knob that trades batch occupancy (throughput)
against p99 (docs/serving.md).

The dispatch loop mirrors the training loops' overlap discipline
(engine/async_feed): request tensors go to device via the model's explicit
``place_input`` (``device_put`` with the registered sharding — DeviceFeed's
rule), the compiled per-bucket artifact is invoked WITHOUT a host sync, and
a ``DispatchWindow`` keeps up to K batches in flight with backpressure. A
separate completion thread performs the single designed host sync, slices
per-request rows out of the padded outputs, resolves futures, and records
end-to-end latency. mxlint's ``sync-in-loop`` pass gates the dispatch loop
the same way it gates the trainers' fit loops.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as _np

from ..base import MXNetError
from ..engine.async_feed import DispatchWindow
from .registry import RegisteredModel

__all__ = ["ServingFuture", "ContinuousBatcher"]


class ServingFuture:
    """Handle for one in-flight request: ``result(timeout)`` blocks until
    the completion thread resolves it (numpy outputs, per-request rows)."""

    __slots__ = ("_event", "_result", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise MXNetError("serving request timed out")
        if self._error is not None:
            raise self._error
        return self._result

    def _set_result(self, value):
        self._result = value
        self._event.set()

    def _set_error(self, err: BaseException):
        self._error = err
        self._event.set()


class _Request:
    __slots__ = ("inputs", "rows", "future", "t_enqueue")

    def __init__(self, inputs: Dict[str, _np.ndarray], rows: int):
        self.inputs = inputs
        self.rows = rows
        self.future = ServingFuture()
        self.t_enqueue = time.perf_counter()


class ContinuousBatcher:
    """Aggregates submitted requests into padded bucket batches for one
    ``RegisteredModel`` and keeps up to ``max_inflight`` batches in flight.

    ``submit()`` never blocks on the device; ``close()`` drains in-flight
    work (pending requests are still served) and joins both worker threads.
    """

    def __init__(self, model: RegisteredModel, max_wait_ms: float = 5.0,
                 max_inflight: int = 2, name: Optional[str] = None):
        self._model = model
        self._name = name or model.name
        self._max_wait = max(float(max_wait_ms), 0.0) / 1e3
        self._pending: "deque[_Request]" = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._window = DispatchWindow(depth=max_inflight,
                                      name=f"serving:{self._name}")
        # bounded: a slow completion sync backpressures dispatch in
        # addition to the window's device-side bound
        self._done_q: "queue.Queue" = queue.Queue(
            maxsize=max(int(max_inflight), 1) + 1)
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name=f"mx-serving-dispatch-{self._name}")
        self._completer = threading.Thread(
            target=self._complete_loop, daemon=True,
            name=f"mx-serving-complete-{self._name}")
        self._dispatcher.start()
        self._completer.start()

    # -- enqueue -------------------------------------------------------------
    def _validate(self, named: Dict[str, Any]) -> Tuple[Dict[str,
                                                             _np.ndarray],
                                                        int]:
        model = self._model
        unknown = [n for n in named if n not in model.input_names]
        if unknown:
            raise MXNetError(
                f"submit: unknown inputs {unknown}; model "
                f"{model.name!r} takes {model.input_names}")
        missing = [n for n in model.input_names if n not in named]
        if missing:
            raise MXNetError(
                f"submit: missing inputs {missing}; model "
                f"{model.name!r} takes {model.input_names}")
        arrays = {}
        rows = None
        for n in model.input_names:
            a = _np.asarray(named[n], dtype=model.input_dtype(n))
            want = model.row_shape(n)
            if a.shape == want:  # a single row: auto-lift to batch 1
                a = a[None]
            if a.ndim != len(want) + 1 or a.shape[1:] != want:
                raise MXNetError(
                    f"submit: input {n!r} has shape {a.shape}; expected "
                    f"(rows,)+{want}")
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise MXNetError(
                    f"submit: inputs disagree on rows "
                    f"({rows} vs {a.shape[0]} for {n!r})")
            arrays[n] = a
        if rows is None or rows < 1:
            raise MXNetError("submit: empty request")
        if rows > model.max_bucket:
            raise MXNetError(
                f"submit: {rows} rows exceed the largest bucket "
                f"{model.max_bucket} of model {model.name!r}; split the "
                "request or register a larger bucket")
        return arrays, rows

    def submit(self, inputs: Optional[Dict[str, Any]] = None,
               **named) -> ServingFuture:
        """Enqueue one request (dict or kwargs of input name -> array with
        leading batch dim, or a bare row). Returns immediately."""
        merged = dict(inputs or {})
        merged.update(named)
        arrays, rows = self._validate(merged)
        req = _Request(arrays, rows)
        with self._cond:
            if self._closed:
                raise MXNetError(
                    f"serving queue for {self._name!r} is closed")
            self._pending.append(req)
            depth = len(self._pending)
            self._cond.notify_all()
        from .. import telemetry as _telem
        if _telem._ENABLED:
            _telem.record_serving_enqueue(self._name, rows)
            _telem.record_serving_queue_depth(self._name, depth)
        return req.future

    # -- batch formation -----------------------------------------------------
    def _take_locked(self) -> Tuple[List[_Request], int]:
        """Pop the longest request prefix fitting the largest bucket.
        Caller holds the lock."""
        take: List[_Request] = []
        rows = 0
        while self._pending and \
                rows + self._pending[0].rows <= self._model.max_bucket:
            req = self._pending.popleft()
            take.append(req)
            rows += req.rows
        return take, rows

    def _next_batch(self) -> Optional[Tuple[List[_Request], int, int, int]]:
        """Block until a batch is ready under the dispatch policy; None on
        shutdown with an empty queue."""
        with self._cond:
            while True:
                if self._pending:
                    head_rows = 0
                    n_fit = 0
                    for req in self._pending:
                        if head_rows + req.rows > self._model.max_bucket:
                            break
                        head_rows += req.rows
                        n_fit += 1
                    deadline = self._pending[0].t_enqueue + self._max_wait
                    now = time.perf_counter()
                    full = head_rows >= self._model.max_bucket or \
                        n_fit < len(self._pending)
                    if full or self._closed or now >= deadline:
                        take, rows = self._take_locked()
                        depth = len(self._pending)
                        bucket = self._model.smallest_bucket(rows)
                        return take, bucket, rows, depth
                    self._cond.wait(timeout=deadline - now)
                elif self._closed:
                    return None
                else:
                    self._cond.wait()

    def _assemble(self, reqs: List[_Request], bucket: int) -> Dict[str, Any]:
        """Concatenate + zero-pad the requests' host arrays to the bucket
        shape and place each tensor on device with the model's explicit
        sharding (the one H2D transfer, off the compiled call)."""
        feed = {}
        for n in self._model.input_names:
            parts = [r.inputs[n] for r in reqs]
            rows = sum(p.shape[0] for p in parts)
            if rows < bucket:
                pad = _np.zeros((bucket - rows,) + self._model.row_shape(n),
                                dtype=parts[0].dtype)
                parts.append(pad)
            host = parts[0] if len(parts) == 1 \
                else _np.concatenate(parts, axis=0)
            feed[n] = self._model.place_input(n, host)
        return feed

    # -- dispatch / completion ----------------------------------------------
    def _dispatch_loop(self):
        from .. import telemetry as _telem
        while True:
            batch = self._next_batch()
            if batch is None:
                break
            reqs, bucket, rows, depth = batch
            try:
                feed = self._assemble(reqs, bucket)
                outs = self._model.forward(bucket, feed)
            except BaseException as e:  # fail THIS batch, keep serving
                for r in reqs:
                    r.future._set_error(e)
                if _telem._ENABLED:
                    for r in reqs:
                        _telem.record_serving_completion(
                            self._name,
                            time.perf_counter() - r.t_enqueue,
                            r.rows, status="error")
                continue
            # bounded in-flight: blocks on the OLDEST batch when > K are
            # outstanding — backpressure, never a sync on `outs`
            self._window.admit(outs)
            if _telem._ENABLED:
                _telem.record_serving_dispatch(self._name, bucket, rows)
                _telem.record_serving_queue_depth(self._name, depth)
            self._done_q.put((reqs, outs))
        self._done_q.put(None)

    def _complete_loop(self):
        while True:
            item = self._done_q.get()
            if item is None:
                break
            self._complete(*item)

    def _complete(self, reqs: List[_Request], outs):
        """The designed host sync: read the padded outputs back, slice each
        request's real rows, resolve futures, record end-to-end latency."""
        from .. import telemetry as _telem
        try:
            host = [_np.asarray(o) for o in outs]
        except BaseException as e:
            for r in reqs:
                r.future._set_error(e)
                if _telem._ENABLED:
                    _telem.record_serving_completion(
                        self._name, time.perf_counter() - r.t_enqueue,
                        r.rows, status="error")
            return
        off = 0
        for r in reqs:
            sl = [h[off:off + r.rows] for h in host]
            off += r.rows
            r.future._set_result(sl[0] if len(sl) == 1 else sl)
            if _telem._ENABLED:
                _telem.record_serving_completion(
                    self._name, time.perf_counter() - r.t_enqueue, r.rows)

    # -- lifecycle -----------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._pending)

    def close(self, timeout: float = 30.0):
        """Stop accepting requests, serve everything already queued, join
        the workers."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._dispatcher.join(timeout=timeout)
        self._completer.join(timeout=timeout)
        self._window.drain()

    def __del__(self):
        try:
            self.close(timeout=1.0)
        except Exception:
            pass
