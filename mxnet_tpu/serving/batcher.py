"""Continuous batcher: thread-safe request queue -> bucketed padded batches.

The TPU-native continuous/dynamic batching policy (ROADMAP item 3): XLA
artifacts are fixed-shape, so instead of arbitrary dynamic batches the
batcher aggregates in-flight requests into the SMALLEST covering bucket
from the model's configured bucket set (e.g. 1/8/64), pads the tail rows,
and slices real rows back per request at completion. Latency is bounded:
a batch dispatches as soon as (a) it fills the largest bucket, (b) the next
queued request can no longer fit, or (c) the OLDEST queued request has
waited ``max_wait_ms`` — the knob that trades batch occupancy (throughput)
against p99 (docs/serving.md).

The dispatch loop mirrors the training loops' overlap discipline
(engine/async_feed): request tensors go to device via the model's explicit
``place_input`` (``device_put`` with the registered sharding — DeviceFeed's
rule), the compiled per-bucket artifact is invoked WITHOUT a host sync, and
a ``DispatchWindow`` keeps up to K batches in flight with backpressure. A
separate completion thread performs the single designed host sync, slices
per-request rows out of the padded outputs, resolves futures, and records
end-to-end latency. mxlint's ``sync-in-loop`` pass gates the dispatch loop
the same way it gates the trainers' fit loops.

Graceful degradation under overload (docs/reliability.md):

  - **Admission control.** ``max_queue`` bounds the pending queue; an
    over-bound ``submit`` raises :class:`ServerOverloaded` immediately
    (the HTTP front door maps it to 503 + ``Retry-After``) and books
    ``mx_requests_shed_total{reason="queue_full"}`` — shedding at the
    door beats queueing work the SLO already lost.
  - **Deadlines.** ``submit(deadline_ms=...)`` bounds how long a request
    may WAIT; batch formation drops expired requests (resolved with
    :class:`DeadlineExceeded`, never dispatched) so a backlog drains to
    live work instead of computing dead answers. ``result(timeout)``
    additionally CANCELS a still-queued request on timeout, reclaiming
    the queue slot.
  - **Priority classes.** Two classes — ``latency`` (default) and
    ``batch`` — with strict priority at batch formation: latency requests
    fill the bucket first, so a heavy bulk workload cannot starve the
    latency-sensitive one (SLO asserted on the per-model
    ``mx_serving_request_seconds`` histogram).
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as _np

from .. import faults as _faults
from ..base import MXNetError, env
from ..engine.async_feed import DispatchWindow
from ..telemetry import tracing as _tracing
from .registry import RegisteredModel

__all__ = ["ServingFuture", "ContinuousBatcher", "ServerOverloaded",
           "DeadlineExceeded", "PRIORITIES"]

env.declare("MXNET_TPU_SERVING_MAX_QUEUE", 0, int,
            "Default per-model serving admission bound: submit() sheds "
            "(ServerOverloaded / HTTP 503) when this many requests are "
            "already queued; 0 = unbounded")

PRIORITIES = ("latency", "batch")


class ServerOverloaded(MXNetError):
    """The request was shed at admission: the pending queue is at its
    ``max_queue`` bound. Retry after backoff (HTTP callers get 503 with
    ``Retry-After``)."""


class DeadlineExceeded(MXNetError):
    """The request's deadline passed before a result was ready — it was
    either dropped while queued (never dispatched) or abandoned by the
    caller's ``result(timeout)``."""


class ServingFuture:
    """Handle for one in-flight request: ``result(timeout)`` blocks until
    the completion thread resolves it (numpy outputs, per-request rows).

    On timeout the future first tries to CANCEL the request; if it was
    still queued, the slot is reclaimed and :class:`DeadlineExceeded`
    raises immediately. A request already dispatched to the device cannot
    be recalled — ``result`` then waits one more ``timeout`` grace period
    for the in-flight batch before giving up (the completion thread still
    resolves the future; a later ``result()`` call returns it)."""

    __slots__ = ("_event", "_result", "_error", "_batcher", "_request")

    def __init__(self, batcher: Optional["ContinuousBatcher"] = None,
                 request: Optional["_Request"] = None):
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self._batcher = batcher
        self._request = request

    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> bool:
        """Remove the request from the pending queue if it has not been
        taken for dispatch yet. True if cancelled (the future resolves
        with :class:`DeadlineExceeded`); False if already dispatched or
        resolved."""
        b, r = self._batcher, self._request
        if b is None or r is None or self._event.is_set():
            return False
        return b._cancel(r)

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            if self.cancel():
                raise DeadlineExceeded(
                    f"serving request timed out after {timeout}s while "
                    "queued (cancelled, slot reclaimed)")
            if not self._event.wait(timeout):
                raise DeadlineExceeded(
                    f"serving request timed out after {timeout}s grace "
                    "with its batch still in flight")
        if self._error is not None:
            raise self._error
        return self._result

    def _set_result(self, value):
        self._result = value
        self._event.set()

    def _set_error(self, err: BaseException):
        self._error = err
        self._event.set()

    @property
    def trace_id(self) -> Optional[str]:
        """The request's tracing trace id (None when tracing is disarmed):
        the same id on every lifecycle span and in the HTTP response's
        ``X-MX-Trace-Id`` header."""
        r = self._request
        t = None if r is None else r.trace
        return None if t is None else t[0]


class _Request:
    __slots__ = ("inputs", "rows", "future", "t_enqueue", "priority",
                 "deadline", "trace")

    def __init__(self, inputs: Dict[str, _np.ndarray], rows: int,
                 priority: str = "latency",
                 deadline_ms: Optional[float] = None):
        self.inputs = inputs
        self.rows = rows
        self.priority = priority
        self.t_enqueue = time.perf_counter()
        self.deadline = None if deadline_ms is None \
            else self.t_enqueue + float(deadline_ms) / 1e3
        self.future = None  # set by the batcher (needs the backref)
        self.trace = None   # (trace_id, root span_id) when tracing is armed


class ContinuousBatcher:
    """Aggregates submitted requests into padded bucket batches for one
    ``RegisteredModel`` and keeps up to ``max_inflight`` batches in flight.

    ``submit()`` never blocks on the device; ``close()`` drains in-flight
    work (pending requests are still served) and joins both worker threads.
    ``max_queue`` bounds admission (default ``MXNET_TPU_SERVING_MAX_QUEUE``,
    0 = unbounded).
    """

    def __init__(self, model: RegisteredModel, max_wait_ms: float = 5.0,
                 max_inflight: int = 2, name: Optional[str] = None,
                 max_queue: Optional[int] = None):
        self._model = model
        self._name = name or model.name
        self._max_wait = max(float(max_wait_ms), 0.0) / 1e3
        self._max_queue = int(env.get("MXNET_TPU_SERVING_MAX_QUEUE")
                              if max_queue is None else max_queue)
        self._pending: Dict[str, "deque[_Request]"] = {
            p: deque() for p in PRIORITIES}
        self._cond = threading.Condition()
        self._closed = False
        self._window = DispatchWindow(depth=max_inflight,
                                      name=f"serving:{self._name}")
        # bounded: a slow completion sync backpressures dispatch in
        # addition to the window's device-side bound
        self._done_q: "queue.Queue" = queue.Queue(
            maxsize=max(int(max_inflight), 1) + 1)
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name=f"mx-serving-dispatch-{self._name}")
        self._completer = threading.Thread(
            target=self._complete_loop, daemon=True,
            name=f"mx-serving-complete-{self._name}")
        self._dispatcher.start()
        self._completer.start()

    # -- enqueue -------------------------------------------------------------
    def _validate(self, named: Dict[str, Any]) -> Tuple[Dict[str,
                                                             _np.ndarray],
                                                        int]:
        model = self._model
        unknown = [n for n in named if n not in model.input_names]
        if unknown:
            raise MXNetError(
                f"submit: unknown inputs {unknown}; model "
                f"{model.name!r} takes {model.input_names}")
        missing = [n for n in model.input_names if n not in named]
        if missing:
            raise MXNetError(
                f"submit: missing inputs {missing}; model "
                f"{model.name!r} takes {model.input_names}")
        arrays = {}
        rows = None
        for n in model.input_names:
            a = _np.asarray(named[n], dtype=model.input_dtype(n))
            want = model.row_shape(n)
            if a.shape == want:  # a single row: auto-lift to batch 1
                a = a[None]
            if a.ndim != len(want) + 1 or a.shape[1:] != want:
                raise MXNetError(
                    f"submit: input {n!r} has shape {a.shape}; expected "
                    f"(rows,)+{want}")
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise MXNetError(
                    f"submit: inputs disagree on rows "
                    f"({rows} vs {a.shape[0]} for {n!r})")
            arrays[n] = a
        if rows is None or rows < 1:
            raise MXNetError("submit: empty request")
        if rows > model.max_bucket:
            raise MXNetError(
                f"submit: {rows} rows exceed the largest bucket "
                f"{model.max_bucket} of model {model.name!r}; split the "
                "request or register a larger bucket")
        return arrays, rows

    def _shed(self, reason: str, n: int = 1):
        """Book shed requests (admission reject / expired / cancelled)."""
        from .. import telemetry as _telem
        if _telem._ENABLED:
            for _ in range(max(int(n), 0)):
                _telem.record_request_shed(self._name, reason)

    def submit(self, inputs: Optional[Dict[str, Any]] = None,
               priority: str = "latency",
               deadline_ms: Optional[float] = None,
               **named) -> ServingFuture:
        """Enqueue one request (dict or kwargs of input name -> array with
        leading batch dim, or a bare row). Returns immediately.

        ``priority`` is ``"latency"`` (strictly preferred at batch
        formation) or ``"batch"``; ``deadline_ms`` bounds queue wait —
        an expired request is dropped, never dispatched, and its future
        raises :class:`DeadlineExceeded`."""
        if priority not in PRIORITIES:
            raise MXNetError(
                f"submit: unknown priority {priority!r}; classes are "
                f"{PRIORITIES}")
        merged = dict(inputs or {})
        merged.update(named)
        arrays, rows = self._validate(merged)
        req = _Request(arrays, rows, priority=priority,
                       deadline_ms=deadline_ms)
        req.future = ServingFuture(self, req)
        if _tracing._ENABLED:
            # the request's root context: the same trace id rides every
            # lifecycle span, the future, and the HTTP response header.
            # Allocated BEFORE the enqueue — the dispatcher may take the
            # request the moment the queue lock drops.
            req.trace = _tracing.new_root(self._name)
        with self._cond:
            if self._closed:
                raise MXNetError(
                    f"serving queue for {self._name!r} is closed")
            depth = self._depth_locked()
            if self._max_queue > 0 and depth >= self._max_queue:
                overloaded = ServerOverloaded(
                    f"serving queue for {self._name!r} is full "
                    f"({depth}/{self._max_queue} requests queued); shed — "
                    "retry with backoff")
            else:
                overloaded = None
                self._pending[priority].append(req)
                depth += 1
                self._cond.notify_all()
        if overloaded is not None:
            self._shed("queue_full")
            raise overloaded
        if _tracing._ENABLED:
            _tracing.event("mx.serving.enqueue", parent=req.trace,
                           model=self._name, rows=rows, priority=priority,
                           depth=depth)
        from .. import telemetry as _telem
        if _telem._ENABLED:
            _telem.record_serving_enqueue(self._name, rows)
            _telem.record_serving_queue_depth(self._name, depth)
        return req.future

    def _cancel(self, req: _Request) -> bool:
        """Remove a still-queued request (future.cancel / result timeout).
        True only if the request had not been taken for dispatch."""
        with self._cond:
            try:
                self._pending[req.priority].remove(req)
            except ValueError:
                return False
        self._shed("cancelled")
        return True

    # -- batch formation -----------------------------------------------------
    def _depth_locked(self) -> int:
        return sum(len(q) for q in self._pending.values())

    def _iter_locked(self):
        """Pending requests in take order: latency class first, FIFO
        within each class."""
        for p in PRIORITIES:
            yield from self._pending[p]

    def _expire_locked(self, now: float) -> List[_Request]:
        """Drop queued requests whose deadline passed (they are resolved
        with DeadlineExceeded by the caller, outside dispatch)."""
        expired: List[_Request] = []
        for p in PRIORITIES:
            q = self._pending[p]
            live = [r for r in q if r.deadline is not None
                    and r.deadline <= now]
            for r in live:
                q.remove(r)
            expired.extend(live)
        return expired

    def _take_locked(self) -> Tuple[List[_Request], int]:
        """Pop the longest latency-first prefix fitting the largest
        bucket. Caller holds the lock."""
        take: List[_Request] = []
        rows = 0
        for p in PRIORITIES:
            q = self._pending[p]
            while q and rows + q[0].rows <= self._model.max_bucket:
                req = q.popleft()
                take.append(req)
                rows += req.rows
        return take, rows

    def _next_batch(self) -> Optional[Tuple[List[_Request], int, int, int,
                                            float]]:
        """Block until a batch is ready under the dispatch policy; None on
        shutdown with an empty queue. The last element is the take-time
        perf_counter stamp — queue-wait accounting and spans reuse it, so
        the breakdown adds no clock reads."""
        from .. import telemetry as _telem
        while True:
            with self._cond:
                now = time.perf_counter()
                expired = self._expire_locked(now)
                if not expired:
                    if self._depth_locked():
                        head_rows = 0
                        n_fit = 0
                        for req in self._iter_locked():
                            if head_rows + req.rows > self._model.max_bucket:
                                break
                            head_rows += req.rows
                            n_fit += 1
                        oldest = min(q[0].t_enqueue
                                     for q in self._pending.values() if q)
                        deadline = oldest + self._max_wait
                        full = head_rows >= self._model.max_bucket or \
                            n_fit < self._depth_locked()
                        if full or self._closed or now >= deadline:
                            take, rows = self._take_locked()
                            depth = self._depth_locked()
                            bucket = self._model.smallest_bucket(rows)
                            return take, bucket, rows, depth, now
                        # wake for the batch deadline OR the nearest
                        # request deadline, whichever is sooner
                        wake = deadline
                        for req in self._iter_locked():
                            if req.deadline is not None:
                                wake = min(wake, req.deadline)
                        self._cond.wait(timeout=max(wake - now, 0.0))
                        continue
                    elif self._closed:
                        return None
                    else:
                        self._cond.wait()
                        continue
            # resolve expired futures OUTSIDE the lock (telemetry +
            # event.set need not serialize batch formation)
            for r in expired:
                r.future._set_error(DeadlineExceeded(
                    f"request deadline passed after "
                    f"{(now - r.t_enqueue) * 1e3:.1f}ms in the "
                    f"{r.priority!r} queue of {self._name!r}; dropped "
                    "before dispatch"))
            self._shed("deadline", n=len(expired))
            if _telem._ENABLED:
                for r in expired:
                    _telem.record_serving_completion(
                        self._name, now - r.t_enqueue, r.rows,
                        status="deadline")
            if _tracing._ENABLED:
                for r in expired:
                    if r.trace is not None:
                        _tracing.record_span(
                            "mx.serving.request", r.t_enqueue, now,
                            ctx=r.trace, model=self._name, status="deadline")

    def _assemble(self, reqs: List[_Request], bucket: int) -> Dict[str, Any]:
        """Concatenate + zero-pad the requests' host arrays to the bucket
        shape and place each tensor on device with the model's explicit
        sharding (the one H2D transfer, off the compiled call)."""
        feed = {}
        for n in self._model.input_names:
            parts = [r.inputs[n] for r in reqs]
            rows = sum(p.shape[0] for p in parts)
            if rows < bucket:
                pad = _np.zeros((bucket - rows,) + self._model.row_shape(n),
                                dtype=parts[0].dtype)
                parts.append(pad)
            host = parts[0] if len(parts) == 1 \
                else _np.concatenate(parts, axis=0)
            feed[n] = self._model.place_input(n, host)
        return feed

    # -- dispatch / completion ----------------------------------------------
    def _dispatch_loop(self):
        from .. import telemetry as _telem
        while True:
            batch = self._next_batch()
            if batch is None:
                break
            reqs, bucket, rows, depth, t_take = batch
            try:
                if _faults._ACTIVE:
                    _faults.check("serving.dispatch")
                t_form0 = time.perf_counter() if _tracing._ENABLED else 0.0
                feed = self._assemble(reqs, bucket)
                t_formed = time.perf_counter() if _tracing._ENABLED else 0.0
                outs = self._model.forward(bucket, feed)
            except Exception as e:  # fail THIS batch, keep serving;
                # KeyboardInterrupt/SystemExit propagate (mxlint
                # broad-except)
                now = time.perf_counter()
                for r in reqs:
                    r.future._set_error(e)
                    if _telem._ENABLED:
                        _telem.record_serving_completion(
                            self._name, now - r.t_enqueue,
                            r.rows, status="error")
                    if _tracing._ENABLED and r.trace is not None:
                        _tracing.record_span(
                            "mx.serving.request", r.t_enqueue, now,
                            ctx=r.trace, model=self._name, status="error",
                            error=type(e).__name__)
                continue
            # bounded in-flight: blocks on the OLDEST batch when > K are
            # outstanding — backpressure, never a sync on `outs`
            self._window.admit(outs)
            if _telem._ENABLED:
                _telem.record_serving_dispatch(self._name, bucket, rows)
                _telem.record_serving_queue_depth(self._name, depth)
                for r in reqs:
                    _telem.record_serving_queue_wait(
                        self._name, t_take - r.t_enqueue)
            if _tracing._ENABLED:
                t_admit = time.perf_counter()
                batch_ref = next((r.trace for r in reqs
                                  if r.trace is not None), None)
                if batch_ref is not None:
                    _tracing.record_span(
                        "mx.serving.batch_form", t_form0, t_formed,
                        parent=batch_ref, model=self._name, bucket=bucket,
                        rows=rows, n_requests=len(reqs))
                for r in reqs:
                    if r.trace is not None:
                        _tracing.record_span(
                            "mx.serving.queue_wait", r.t_enqueue, t_take,
                            parent=r.trace, model=self._name)
                        _tracing.record_span(
                            "mx.serving.dispatch", t_take, t_admit,
                            parent=r.trace, model=self._name, bucket=bucket)
            self._done_q.put((reqs, outs))
        self._done_q.put(None)

    def _complete_loop(self):
        while True:
            item = self._done_q.get()
            if item is None:
                break
            self._complete(*item)

    def _complete(self, reqs: List[_Request], outs):
        """The designed host sync: read the padded outputs back, slice each
        request's real rows, resolve futures, record end-to-end latency."""
        from .. import telemetry as _telem
        t_c0 = time.perf_counter() if _tracing._ENABLED else 0.0
        try:
            host = [_np.asarray(o) for o in outs]
        except Exception as e:  # device-side batch failure; the workers
            # stay up (KeyboardInterrupt/SystemExit propagate)
            now = time.perf_counter()
            for r in reqs:
                r.future._set_error(e)
                if _telem._ENABLED:
                    _telem.record_serving_completion(
                        self._name, now - r.t_enqueue,
                        r.rows, status="error")
                if _tracing._ENABLED and r.trace is not None:
                    _tracing.record_span(
                        "mx.serving.request", r.t_enqueue, now,
                        ctx=r.trace, model=self._name, status="error",
                        error=type(e).__name__)
            return
        off = 0
        now = time.perf_counter()
        for r in reqs:
            sl = [h[off:off + r.rows] for h in host]
            off += r.rows
            if _tracing._ENABLED and r.trace is not None:
                # completion = the sync + row slicing; recorded BEFORE the
                # future resolves so a caller that immediately dumps the
                # ring sees its own request's spans
                _tracing.record_span("mx.serving.complete", t_c0, now,
                                     parent=r.trace, model=self._name)
                _tracing.record_span("mx.serving.request", r.t_enqueue, now,
                                     ctx=r.trace, model=self._name,
                                     rows=r.rows, status="ok")
            r.future._set_result(sl[0] if len(sl) == 1 else sl)
            if _telem._ENABLED:
                _telem.record_serving_completion(
                    self._name, now - r.t_enqueue, r.rows)

    # -- lifecycle -----------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        with self._cond:
            return self._depth_locked()

    def close(self, timeout: float = 30.0):
        """Stop accepting requests, serve everything already queued, join
        the workers."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._dispatcher.join(timeout=timeout)
        self._completer.join(timeout=timeout)
        self._window.drain()

    def __del__(self):
        try:
            self.close(timeout=1.0)
        except Exception:
            pass
