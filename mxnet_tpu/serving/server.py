"""serving.Server: the front door over registry + continuous batchers.

In-process API (futures)::

    srv = serving.Server(max_wait_ms=3.0)
    srv.register("resnet", "model-symbol.json", "model-0000.params",
                 input_shapes={"data": (3, 224, 224)}, buckets=(1, 8, 64))
    fut = srv.submit("resnet", data=batch)      # non-blocking
    out = fut.result(timeout=30)                # numpy, request's own rows
    out = srv.predict("resnet", data=batch)     # submit+result shorthand

HTTP API (stdlib ``http.server``, daemon thread)::

    port = srv.start_http(8000)
    # POST /v1/models/<name>:predict   {"inputs": {"data": [[...], ...]}}
    #   -> {"model": ..., "output_names": [...], "outputs": [[...], ...]}
    # GET  /v1/models                  registry listing + memory budget
    # GET  /metrics                    Prometheus text (mx.telemetry.scrape)

Every worker thread funnels into the same continuous batcher, so HTTP and
in-process callers share buckets, artifacts, and SLO metrics.
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as _np

from ..base import MXNetError
from .batcher import ContinuousBatcher, ServingFuture
from .registry import ModelRegistry, RegisteredModel

__all__ = ["Server"]


class Server:
    """Multi-model serving front door (registry + per-model batcher)."""

    def __init__(self, max_wait_ms: float = 5.0, max_inflight: int = 2,
                 mesh=None, data_spec=None):
        self.registry = ModelRegistry()
        self._batchers: Dict[str, ContinuousBatcher] = {}
        self._max_wait_ms = float(max_wait_ms)
        self._max_inflight = int(max_inflight)
        self._mesh = mesh
        self._data_spec = data_spec
        self._http = None
        self._lock = threading.RLock()

    # -- registration --------------------------------------------------------
    def register(self, name: str, symbol_file: str,
                 param_file: Optional[str] = None,
                 input_shapes: Optional[Dict[str, Sequence[int]]] = None,
                 buckets: Sequence[int] = (1, 8, 64),
                 dtype: str = "float32",
                 dtypes: Optional[Dict[str, str]] = None,
                 max_wait_ms: Optional[float] = None) -> RegisteredModel:
        """Load + warm a model (one compiled artifact per bucket, eagerly,
        possibly straight from the persistent XLA cache) and start its
        batcher. Returns the RegisteredModel."""
        model = self.registry.register(
            name, symbol_file, param_file, input_shapes=input_shapes,
            buckets=buckets, dtype=dtype, dtypes=dtypes,
            mesh=self._mesh, data_spec=self._data_spec)
        with self._lock:
            self._batchers[name] = ContinuousBatcher(
                model,
                max_wait_ms=self._max_wait_ms if max_wait_ms is None
                else max_wait_ms,
                max_inflight=self._max_inflight)
        return model

    def unregister(self, name: str):
        with self._lock:
            batcher = self._batchers.pop(name, None)
        if batcher is not None:
            batcher.close()
        self.registry.unregister(name)

    def models(self) -> List[Dict[str, Any]]:
        out = []
        for name in self.registry.names():
            m = self.registry.get(name)
            out.append({"name": name, "buckets": list(m.buckets),
                        "inputs": {n: list(m.row_shape(n))
                                   for n in m.input_names},
                        "outputs": m.output_names,
                        "param_bytes": m.param_bytes})
        return out

    # -- inference -----------------------------------------------------------
    def _batcher(self, name: str) -> ContinuousBatcher:
        with self._lock:
            try:
                return self._batchers[name]
            except KeyError:
                raise MXNetError(
                    f"unknown model {name!r}; registered: "
                    f"{list(self._batchers)}") from None

    def submit(self, model: str, inputs: Optional[Dict[str, Any]] = None,
               **named) -> ServingFuture:
        """Enqueue a request; returns a future immediately."""
        return self._batcher(model).submit(inputs, **named)

    def predict(self, model: str, inputs: Optional[Dict[str, Any]] = None,
                timeout: float = 60.0, **named):
        """Blocking submit+result convenience."""
        return self.submit(model, inputs, **named).result(timeout)

    # -- HTTP front door -----------------------------------------------------
    def start_http(self, port: int = 0, addr: str = "127.0.0.1") -> int:
        """Serve the JSON predict API + /metrics on a daemon thread;
        returns the bound port (0 picks a free one)."""
        import http.server
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/metrics"):
                    from .. import telemetry as _telem
                    self._send(200, _telem.scrape().encode(),
                               "text/plain; version=0.0.4")
                elif self.path.startswith("/v1/models"):
                    body = json.dumps({
                        "models": server.models(),
                        "total_param_bytes":
                            server.registry.total_param_bytes(),
                    }).encode()
                    self._send(200, body)
                else:
                    self._send(404, b'{"error": "not found"}')

            def do_POST(self):
                path = self.path
                if not (path.startswith("/v1/models/")
                        and path.endswith(":predict")):
                    self._send(404, b'{"error": "not found"}')
                    return
                name = path[len("/v1/models/"):-len(":predict")]
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    inputs = payload.get("inputs", payload)
                    out = server.predict(name, inputs)
                    outs = out if isinstance(out, list) else [out]
                    model = server.registry.get(name)
                    body = json.dumps({
                        "model": name,
                        "output_names": model.output_names,
                        "outputs": [_np.asarray(o).tolist() for o in outs],
                    }).encode()
                    self._send(200, body)
                except Exception as e:
                    self._send(400, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer((addr, port), Handler)
        t = threading.Thread(target=srv.serve_forever, daemon=True,
                             name="mx-serving-http")
        t.start()
        with self._lock:
            self._http = srv
        return srv.server_address[1]

    # -- lifecycle -----------------------------------------------------------
    def close(self):
        """Stop HTTP, drain + join every batcher, release artifact pins."""
        with self._lock:
            http_srv, self._http = self._http, None
            batchers = list(self._batchers.values())
            self._batchers.clear()
        if http_srv is not None:
            http_srv.shutdown()
            http_srv.server_close()
        for b in batchers:
            b.close()
        self.registry.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
