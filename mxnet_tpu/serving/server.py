"""serving.Server: the front door over registry + continuous batchers.

In-process API (futures)::

    srv = serving.Server(max_wait_ms=3.0)
    srv.register("resnet", "model-symbol.json", "model-0000.params",
                 input_shapes={"data": (3, 224, 224)}, buckets=(1, 8, 64))
    fut = srv.submit("resnet", data=batch)      # non-blocking
    out = fut.result(timeout=30)                # numpy, request's own rows
    out = srv.predict("resnet", data=batch)     # submit+result shorthand

HTTP API (stdlib ``http.server``, daemon thread)::

    port = srv.start_http(8000)
    # POST /v1/models/<name>:predict   {"inputs": {"data": [[...], ...]},
    #                                   "priority": "latency"|"batch",
    #                                   "timeout_ms": 500}
    #   -> {"model": ..., "output_names": [...], "outputs": [[...], ...]}
    #   -> 503 + Retry-After when the model's queue sheds (ServerOverloaded)
    #   -> 504 when the request's deadline passed (DeadlineExceeded)
    # GET  /v1/models                  registry listing + memory budget
    # GET  /metrics                    Prometheus text (mx.telemetry.scrape)

Overload never hangs a caller: admission control sheds at the door with
an explicit retry hint, deadlines cancel queued work, and the two
priority classes keep the latency-sensitive model responsive under bulk
traffic (docs/reliability.md).

Every worker thread funnels into the same continuous batcher, so HTTP and
in-process callers share buckets, artifacts, and SLO metrics.
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as _np

from .. import faults as _faults
from ..base import MXNetError
from .batcher import (ContinuousBatcher, DeadlineExceeded, ServerOverloaded,
                      ServingFuture)
from .registry import ModelRegistry, RegisteredModel

__all__ = ["Server"]


class Server:
    """Multi-model serving front door (registry + per-model batcher)."""

    def __init__(self, max_wait_ms: float = 5.0, max_inflight: int = 2,
                 mesh=None, data_spec=None, max_queue: Optional[int] = None):
        self.registry = ModelRegistry()
        self._batchers: Dict[str, ContinuousBatcher] = {}
        self._max_wait_ms = float(max_wait_ms)
        self._max_inflight = int(max_inflight)
        self._max_queue = max_queue
        self._mesh = mesh
        self._data_spec = data_spec
        self._http = None
        self._lock = threading.RLock()

    # -- registration --------------------------------------------------------
    def register(self, name: str, symbol_file: str,
                 param_file: Optional[str] = None,
                 input_shapes: Optional[Dict[str, Sequence[int]]] = None,
                 buckets: Sequence[int] = (1, 8, 64),
                 dtype: str = "float32",
                 dtypes: Optional[Dict[str, str]] = None,
                 max_wait_ms: Optional[float] = None,
                 max_queue: Optional[int] = None) -> RegisteredModel:
        """Load + warm a model (one compiled artifact per bucket, eagerly,
        possibly straight from the persistent XLA cache) and start its
        batcher. ``max_queue`` overrides the server-wide admission bound
        for this model. Returns the RegisteredModel."""
        model = self.registry.register(
            name, symbol_file, param_file, input_shapes=input_shapes,
            buckets=buckets, dtype=dtype, dtypes=dtypes,
            mesh=self._mesh, data_spec=self._data_spec)
        with self._lock:
            self._batchers[name] = ContinuousBatcher(
                model,
                max_wait_ms=self._max_wait_ms if max_wait_ms is None
                else max_wait_ms,
                max_inflight=self._max_inflight,
                max_queue=self._max_queue if max_queue is None
                else max_queue)
        return model

    def unregister(self, name: str):
        with self._lock:
            batcher = self._batchers.pop(name, None)
        if batcher is not None:
            batcher.close()
        self.registry.unregister(name)

    def models(self) -> List[Dict[str, Any]]:
        out = []
        for name in self.registry.names():
            m = self.registry.get(name)
            out.append({"name": name, "buckets": list(m.buckets),
                        "inputs": {n: list(m.row_shape(n))
                                   for n in m.input_names},
                        "outputs": m.output_names,
                        "param_bytes": m.param_bytes})
        return out

    # -- inference -----------------------------------------------------------
    def _batcher(self, name: str) -> ContinuousBatcher:
        with self._lock:
            try:
                return self._batchers[name]
            except KeyError:
                raise MXNetError(
                    f"unknown model {name!r}; registered: "
                    f"{list(self._batchers)}") from None

    def submit(self, model: str, inputs: Optional[Dict[str, Any]] = None,
               priority: str = "latency",
               deadline_ms: Optional[float] = None,
               **named) -> ServingFuture:
        """Enqueue a request; returns a future immediately. May raise
        ``ServerOverloaded`` (queue at its admission bound — retry with
        backoff). ``deadline_ms`` bounds queue wait; ``priority`` is
        ``"latency"`` or ``"batch"``."""
        return self._batcher(model).submit(
            inputs, priority=priority, deadline_ms=deadline_ms, **named)

    def predict(self, model: str, inputs: Optional[Dict[str, Any]] = None,
                timeout: float = 60.0, priority: str = "latency",
                deadline_ms: Optional[float] = None, **named):
        """Blocking submit+result convenience. The queue-wait deadline
        defaults to ``timeout`` so a result timeout also cancels the
        queued work instead of leaking the slot."""
        if deadline_ms is None and timeout is not None:
            deadline_ms = float(timeout) * 1e3
        return self.submit(model, inputs, priority=priority,
                           deadline_ms=deadline_ms, **named).result(timeout)

    # -- HTTP front door -----------------------------------------------------
    def start_http(self, port: int = 0, addr: str = "127.0.0.1") -> int:
        """Serve the JSON predict API + /metrics on a daemon thread;
        returns the bound port (0 picks a free one). /statusz carries the
        full telemetry.statusz() debug snapshot — including the goodput
        waterfall section (per-category totals, goodput ratio, straggler
        scores when booked) — plus this server's model/queue view."""
        import http.server
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json",
                      headers: Optional[Dict[str, str]] = None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/metrics"):
                    from .. import telemetry as _telem
                    self._send(200, _telem.scrape().encode(),
                               "text/plain; version=0.0.4")
                elif self.path.startswith("/v1/models"):
                    body = json.dumps({
                        "models": server.models(),
                        "total_param_bytes":
                            server.registry.total_param_bytes(),
                    }).encode()
                    self._send(200, body)
                elif self.path.startswith("/statusz"):
                    from .. import telemetry as _telem
                    with server._lock:
                        queues = {n: b.queue_depth
                                  for n, b in server._batchers.items()}
                    body = json.dumps(_telem.statusz(extra={
                        "serving": {
                            "models": server.models(),
                            "total_param_bytes":
                                server.registry.total_param_bytes(),
                            "queue_depth": queues,
                        }}), default=str).encode()
                    self._send(200, body)
                elif self.path.startswith("/healthz"):
                    self._send(200, b'{"status": "ok"}')
                else:
                    self._send(404, b'{"error": "not found"}')

            def do_POST(self):
                path = self.path
                if not (path.startswith("/v1/models/")
                        and path.endswith(":predict")):
                    self._send(404, b'{"error": "not found"}')
                    return
                name = path[len("/v1/models/"):-len(":predict")]
                trace_hdr: Optional[Dict[str, str]] = None
                try:
                    if _faults._ACTIVE:
                        _faults.check("serving.http")
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    inputs = payload.get("inputs", payload)
                    priority = payload.get("priority", "latency")
                    timeout_ms = payload.get("timeout_ms")
                    timeout = 60.0 if timeout_ms is None \
                        else float(timeout_ms) / 1e3
                    # submit + result (not predict) so the request's trace
                    # id is in hand for the X-MX-Trace-Id response header
                    fut = server.submit(name, inputs, priority=priority,
                                        deadline_ms=float(timeout) * 1e3)
                    if fut.trace_id is not None:
                        trace_hdr = {"X-MX-Trace-Id": fut.trace_id}
                    out = fut.result(timeout)
                    outs = out if isinstance(out, list) else [out]
                    model = server.registry.get(name)
                    body = json.dumps({
                        "model": name,
                        "output_names": model.output_names,
                        "outputs": [_np.asarray(o).tolist() for o in outs],
                    }).encode()
                    self._send(200, body, headers=trace_hdr)
                except (ServerOverloaded, _faults.FaultInjected) as e:
                    # graceful degradation: shed with an explicit retry
                    # hint instead of queueing doomed work
                    self._send(503, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        headers=dict(trace_hdr or {}, **{"Retry-After": "1"}))
                except DeadlineExceeded as e:
                    self._send(504, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        headers=trace_hdr)
                except Exception as e:
                    self._send(400, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        headers=trace_hdr)

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer((addr, port), Handler)
        t = threading.Thread(target=srv.serve_forever, daemon=True,
                             name="mx-serving-http")
        t.start()
        with self._lock:
            self._http = srv
        return srv.server_address[1]

    # -- lifecycle -----------------------------------------------------------
    def close(self):
        """Stop HTTP, drain + join every batcher, release artifact pins."""
        with self._lock:
            http_srv, self._http = self._http, None
            batchers = list(self._batchers.values())
            self._batchers.clear()
        if http_srv is not None:
            http_srv.shutdown()
            http_srv.server_close()
        for b in batchers:
            b.close()
        self.registry.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
