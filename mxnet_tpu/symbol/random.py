"""``mx.sym.random`` — symbolic sampling namespace
(reference python/mxnet/symbol/random.py: uniform/normal/multinomial
wrappers over the `_random_*`/`_sample_*` registered ops).

Each function builds a graph node whose `key` input is auto-created as an
RNG variable (symbol.py `__rng__` attr); the executor splits a fresh
threefry key across all RNG nodes every forward, so re-running the same
executor draws new samples — the symbolic analog of the reference's
per-forward resource RNG."""
from __future__ import annotations

from . import _apply_op
from ..ops.registry import get_op as _get_op


def _shape(shape):
    if shape is None:
        return (1,)
    return (shape,) if isinstance(shape, int) else tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", **kwargs):
    """reference symbol/random.py uniform."""
    return _apply_op(_get_op("_random_uniform"), low=low, high=high,
                     shape=_shape(shape), dtype=dtype, **kwargs)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", **kwargs):
    """reference symbol/random.py normal."""
    return _apply_op(_get_op("_random_normal"), loc=loc, scale=scale,
                     shape=_shape(shape), dtype=dtype, **kwargs)


def uniform_like(data, low=0.0, high=1.0, **kwargs):
    return _apply_op(_get_op("_random_uniform_like"), data, low=low,
                     high=high, **kwargs)


def normal_like(data, loc=0.0, scale=1.0, **kwargs):
    return _apply_op(_get_op("_random_normal_like"), data, loc=loc,
                     scale=scale, **kwargs)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kwargs):
    """reference symbol/random.py multinomial (samples category indices
    from probability rows)."""
    return _apply_op(_get_op("_sample_multinomial"), data, shape=shape,
                     get_prob=get_prob, dtype=dtype, **kwargs)
