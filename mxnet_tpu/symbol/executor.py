"""Executor: compiled evaluation of a Symbol graph.

Reference: src/executor/graph_executor.cc (Bind :2043, SimpleBind :1959,
Forward :80, Backward :93) + python/mxnet/executor.py. TPU-native redesign
(SURVEY.md §7): instead of a memory-planned per-op engine schedule, ``bind``
lowers the whole DAG to ONE jitted XLA computation per (is_train) mode.
A training ``forward`` runs the `jax.vjp`-based artifact that also emits the
VJP residuals; ``backward`` then invokes the compiled pullback on those
residuals — the forward computation runs exactly once per step (the
reference's single-GraphExecutor-artifact contract). The old
rematerialize-the-forward backward (the reference's mirror-recompute,
gradient.cc:147) remains only as a fallback for ``backward`` calls with no
preceding training forward. Compiled runners are shared process-wide through
``mxnet_tpu.engine`` keyed on the symbol-graph fingerprint, so N executors
bound to the same graph compile once.

BatchNorm auxiliary-state semantics (reference mutates aux in-op): the
executor computes the momentum blend of the batch statistics as extra traced
outputs and writes them back into ``aux_arrays`` after each training forward.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError, default_dtype
from ..context import Context, current_context
from ..ndarray import NDArray
from .. import ndarray as nd
from .. import engine as _engine
from .symbol import (Symbol, _Node, _num_outputs, _resolved_params,
                     _op_param_names)

__all__ = ["Executor"]


def _graph_runner(symbol: Symbol, is_train: bool):
    """Build the pure function (arg_vals, aux_vals, rng_key) ->
    (outputs, aux_updates) by a topological walk over the DAG."""
    topo = symbol._topo()
    arg_nodes = [n for n in topo if n.kind == "var" and not n.is_aux()
                 and not n.is_rng()]
    aux_nodes = [n for n in topo if n.kind == "var" and n.is_aux()]
    rng_nodes = [n for n in topo if n.kind == "var" and n.is_rng()]
    heads = symbol._heads

    def run(arg_vals: Tuple, aux_vals: Tuple, rng_key):
        _engine.record_trace()
        env: Dict[int, Tuple] = {}
        for node, val in zip(arg_nodes, arg_vals):
            env[id(node)] = (val,)
        for node, val in zip(aux_nodes, aux_vals):
            env[id(node)] = (val,)
        if rng_nodes:
            keys = jax.random.split(rng_key, len(rng_nodes))
            for node, k in zip(rng_nodes, keys):
                env[id(node)] = (k,)
        aux_updates: Dict[int, Any] = {}
        for node in topo:
            if node.kind == "var":
                continue
            ins = [env[id(i)][oi] for i, oi in node.inputs]
            params = _resolved_params(node, training=is_train)
            outs = node.op.unbound(params)(*ins)
            if not isinstance(outs, tuple):
                outs = (outs,)
            node.num_outputs = len(outs)
            env[id(node)] = outs
            if node.op.name == "BatchNorm" and is_train \
                    and not params.get("use_global_stats", False):
                momentum = float(params.get("momentum", 0.9))
                _, bmean, bvar = outs
                for (inp, _), argpos in zip(node.inputs[3:5], (1, 2)):
                    if inp.kind == "var" and inp.is_aux():
                        old = env[id(inp)][0]
                        newv = outs[argpos]
                        aux_updates[id(inp)] = (
                            momentum * old.astype(jnp.float32)
                            + (1.0 - momentum) * newv).astype(old.dtype)
        out_vals = tuple(env[id(n)][oi] for n, oi in heads)
        upd = tuple(aux_updates.get(id(n), env[id(n)][0]) for n in aux_nodes)
        return out_vals, upd

    return run, arg_nodes, aux_nodes, rng_nodes


class _VjpArtifact:
    """Compiled train-mode forward+pullback pair for one (graph, wrt) key:
    ``fwd_res`` emits (outputs, aux updates, residuals); ``bwd`` applies the
    pullback to saved residuals without re-running the forward."""

    __slots__ = ("fwd_res", "bwd", "arg_nodes", "aux_nodes", "cost",
                 "bwd_cost")

    def __init__(self, symbol: Symbol, wrt_names: Tuple[str, ...]):
        run, arg_nodes, aux_nodes, _ = _graph_runner(symbol, True)
        arg_names_all = [n.name for n in arg_nodes]
        wrt_idx = [arg_names_all.index(n) for n in wrt_names]
        holder = {"treedef": None}

        def fwd_res(arg_vals, aux_vals, rng_key):
            sel = tuple(arg_vals[i] for i in wrt_idx)

            def f(sel_vals):
                vals = list(arg_vals)
                for i, v in zip(wrt_idx, sel_vals):
                    vals[i] = v
                return run(tuple(vals), aux_vals, rng_key)

            outs, vjp_fn, upd = jax.vjp(f, sel, has_aux=True)
            leaves, treedef = jax.tree_util.tree_flatten(vjp_fn)
            holder["treedef"] = treedef
            return outs, upd, tuple(leaves)

        def bwd(res_leaves, cots):
            vjp_fn = jax.tree_util.tree_unflatten(holder["treedef"],
                                                  list(res_leaves))
            (grads,) = vjp_fn(tuple(cots))
            return grads

        self.fwd_res = jax.jit(fwd_res)
        self.bwd = jax.jit(bwd)
        self.arg_nodes = arg_nodes
        self.aux_nodes = aux_nodes
        self.cost = None      # fwd cost_analysis, captured at first forward
        self.bwd_cost = None  # pullback cost, captured at first backward


class Executor:
    """Holds bound argument/gradient/aux arrays + the compiled graph."""

    def __init__(self, symbol: Symbol, ctx: Context,
                 arg_dict: "Dict[str, NDArray]",
                 grad_dict: "Dict[str, Optional[NDArray]]",
                 grad_req: Dict[str, str],
                 aux_dict: "Dict[str, NDArray]"):
        self._symbol = symbol
        self._ctx = ctx
        self.arg_dict = arg_dict
        self.grad_dict = grad_dict
        self.aux_dict = aux_dict
        self._grad_req = grad_req
        self.outputs: List[NDArray] = []
        self._runner_cache: Dict[bool, Any] = {}
        self._bwd_cache: Dict[Any, Any] = {}
        self._vjp_artifact: Optional[_VjpArtifact] = None
        self._residuals: Optional[Tuple] = None
        self._fingerprint_memo: Optional[str] = None
        self._rng_seed = 0
        self._last_key = None
        self._monitor_callback = None

    # -- construction --------------------------------------------------------
    @staticmethod
    def _ctx_of(ctx) -> Context:
        return ctx if isinstance(ctx, Context) else current_context()

    @classmethod
    def _bind(cls, symbol: Symbol, ctx, args, args_grad, grad_req, aux_states):
        ctx = cls._ctx_of(ctx)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        dupes = {n for n in arg_names if arg_names.count(n) > 1}
        if dupes:
            raise MXNetError(
                f"bind: duplicate argument names {sorted(dupes)} — two "
                "distinct variables share a name; reuse the SAME Variable "
                "object for shared weights")

        def to_dict(vals, names, what):
            if vals is None:
                return {}
            if isinstance(vals, dict):
                return {k: (v if isinstance(v, NDArray) else nd.array(v))
                        for k, v in vals.items()}
            if len(vals) != len(names):
                raise MXNetError(
                    f"bind: {what} has {len(vals)} entries, expected "
                    f"{len(names)} ({names})")
            return {k: (v if isinstance(v, NDArray) else nd.array(v))
                    for k, v in zip(names, vals)}

        arg_dict = to_dict(args, arg_names, "args")
        missing = [n for n in arg_names if n not in arg_dict]
        if missing:
            raise MXNetError(f"bind: missing argument arrays for {missing}")
        aux_dict = to_dict(aux_states, aux_names, "aux_states")
        for n in aux_names:
            if n not in aux_dict:
                raise MXNetError(f"bind: missing auxiliary state {n}")

        if isinstance(grad_req, str):
            req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            req = dict(zip(arg_names, grad_req))
        else:
            req = {n: grad_req.get(n, "null") for n in arg_names}
        grad_dict = to_dict(args_grad, arg_names, "args_grad")
        with ctx:  # allocate on the executor's context, not the default
            for n in arg_names:
                if req.get(n, "null") != "null" and n not in grad_dict:
                    grad_dict[n] = nd.zeros(arg_dict[n].shape,
                                            dtype=arg_dict[n].dtype)
        return cls(symbol, ctx, arg_dict, grad_dict, req, aux_dict)

    @classmethod
    def _simple_bind(cls, symbol: Symbol, ctx, grad_req, type_dict, kwargs):
        ctx = cls._ctx_of(ctx)
        shapes = {k: tuple(v) for k, v in kwargs.items()}
        dtypes = dict(type_dict or {})
        arg_s, _, aux_s, arg_t, _, aux_t = symbol._infer(shapes, dtypes,
                                                         partial=False)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        arg_dict = {}
        aux_dict = {}
        with ctx:  # arrays live on the executor's context (multi-ctx Module
            # binds replica executors on distinct devices)
            for name, s, t in zip(arg_names, arg_s, arg_t):
                if s is None:
                    raise MXNetError(
                        f"simple_bind: could not infer shape of {name}")
                arg_dict[name] = nd.zeros(s, dtype=t)
            for name, s, t in zip(aux_names, aux_s, aux_t):
                init = nd.ones if name.endswith("_var") or name.endswith("var") \
                    else nd.zeros
                aux_dict[name] = init(s, dtype=t)
        return cls._bind(symbol, ctx, arg_dict, None, grad_req, aux_dict)

    # -- properties ----------------------------------------------------------
    @property
    def arg_arrays(self) -> List[NDArray]:
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self) -> List[Optional[NDArray]]:
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self) -> List[NDArray]:
        return [self.aux_dict[n] for n in self._symbol.list_auxiliary_states()]

    @property
    def output_dict(self) -> "Dict[str, NDArray]":
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    # -- execution -----------------------------------------------------------
    def _fingerprint(self) -> str:
        if self._fingerprint_memo is None:
            try:
                self._fingerprint_memo = _engine.graph_fingerprint(
                    self._symbol.tojson())
            except Exception:
                # unserializable graph: private (per-instance) cache keys
                self._fingerprint_memo = f"executor-{id(self)}"
        return self._fingerprint_memo

    def _fwd(self, is_train: bool):
        cached = self._runner_cache.get(is_train)
        if cached is None:
            key = ("executor", self._fingerprint(), bool(is_train))
            cached = _engine.lookup(key)
            if cached is None:
                with _engine.compile_timer("executor:fwd"):
                    run, arg_nodes, aux_nodes, rng_nodes = _graph_runner(
                        self._symbol, is_train)
                    cached = (jax.jit(run), arg_nodes, aux_nodes, rng_nodes)
                _engine.insert(key, cached)
            self._runner_cache[is_train] = cached
        return cached

    def _wrt_names(self) -> Tuple[str, ...]:
        return tuple(n for n in self._symbol.list_arguments()
                     if self._grad_req.get(n, "null") != "null")

    def _fwd_vjp(self) -> _VjpArtifact:
        art = self._vjp_artifact
        if art is None:
            key = ("executor_vjp", self._fingerprint(), self._wrt_names())
            art = _engine.lookup(key)
            if art is None:
                with _engine.compile_timer("executor:vjp"):
                    art = _VjpArtifact(self._symbol, self._wrt_names())
                _engine.insert(key, art)
            self._vjp_artifact = art
        return art

    def _next_key(self):
        self._rng_seed += 1
        return jax.random.PRNGKey(self._rng_seed)

    def _current_key(self):
        # backward must replay the SAME dropout masks as the most recent
        # TRAINING forward (an intervening eval forward must not disturb it)
        if self._last_key is None:
            self._last_key = self._next_key()
        return self._last_key

    def forward(self, is_train: bool = False, **kwargs) -> List[NDArray]:
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"forward: unknown argument {k}")
            self.arg_dict[k]._set_data(
                (v.handle if isinstance(v, NDArray) else jnp.asarray(v)))
        use_vjp = bool(is_train) and bool(self._wrt_names())
        if use_vjp:
            # training forward through the vjp artifact: outputs + aux
            # updates + residuals in ONE compiled call; backward() replays
            # the pullback on the saved residuals (no forward recompute)
            art = self._fwd_vjp()
            arg_nodes, aux_nodes = art.arg_nodes, art.aux_nodes
        else:
            fn, arg_nodes, aux_nodes, _ = self._fwd(bool(is_train))
        arg_vals = tuple(self.arg_dict[n.name].handle for n in arg_nodes)
        aux_vals = tuple(self.aux_dict[n.name].handle for n in aux_nodes)
        key = self._next_key()
        if is_train:
            self._last_key = key
        if use_vjp:
            from .. import telemetry as _telem
            if _telem._ENABLED and art.cost is None:
                # one AOT lower+compile per artifact (shares XLA caches):
                # FLOPs+bytes for the MFU gauge and the roofline ledger
                art.cost = _engine.estimate_cost(
                    art.fwd_res, arg_vals, aux_vals, key,
                    kind="executor_fwd")
            outs, aux_upd, res = art.fwd_res(arg_vals, aux_vals, key)
            self._residuals = (art, res,
                               tuple((tuple(o.shape), o.dtype) for o in outs))
            c = art.cost or {}
            _engine.record_execution(
                "fwd", c.get("flops", 0.0),
                bytes_accessed=c.get("bytes_accessed", 0.0),
                region=f"executor#{self._fingerprint()[:6]}"
                if _telem._ENABLED else None, cost=c)
        else:
            outs, aux_upd = fn(arg_vals, aux_vals, key)
            _engine.record_execution("fwd")
        if is_train:
            for node, newv in zip(aux_nodes, aux_upd):
                self.aux_dict[node.name]._set_data(newv)
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        if self._monitor_callback is not None:
            for name, arr in self.output_dict.items():
                self._monitor_callback(name, arr)
        return self.outputs

    def _head_cotangents(self, out_grads, out_avals):
        """Normalize user head grads into concrete cotangents matching the
        forward outputs' shapes AND dtypes (a mismatched-dtype out_grads
        must cast, never reuse a stale compiled entry — the cache key
        includes dtypes and the values are cast before the pullback)."""
        nout = len(out_avals)
        if out_grads is None:
            heads: List[Optional[Any]] = [None] * nout
        else:
            if isinstance(out_grads, (NDArray, jnp.ndarray, _np.ndarray)):
                out_grads = [out_grads]
            heads = [g.handle if isinstance(g, NDArray) else jnp.asarray(g)
                     for g in out_grads]
        return tuple(
            jnp.ones(s, t) if h is None
            else (h if h.dtype == t else h.astype(t))
            for h, (s, t) in zip(heads, out_avals))

    def backward(self, out_grads=None, is_train: bool = True):
        wrt_names = list(self._wrt_names())
        if not wrt_names:
            return
        if self._residuals is not None:
            # hot path: compiled pullback over the residuals saved by the
            # last training forward
            art, res, out_avals = self._residuals
            heads = self._head_cotangents(out_grads, out_avals)
            from .. import telemetry as _telem
            if _telem._ENABLED and art.bwd_cost is None:
                c = _engine.estimate_cost(art.bwd, res, heads,
                                          kind="executor_bwd")
                if not c.get("flops"):
                    # 2x-forward roofline convention, flagged estimated
                    c = {"flops": 2.0 * (art.cost or {}).get("flops", 0.0),
                         "estimated": 1.0}
                art.bwd_cost = c
            grads = art.bwd(res, heads)
            c = art.bwd_cost or {}
            _engine.record_execution(
                "bwd", c.get("flops", 0.0),
                bytes_accessed=c.get("bytes_accessed", 0.0),
                region=f"executor#{self._fingerprint()[:6]}/bwd"
                if _telem._ENABLED else None,
                estimated=bool(c.get("estimated")), cost=c)
        else:
            grads = self._backward_recompute(wrt_names, out_grads)
        for name, g in zip(wrt_names, grads):
            tgt = self.grad_dict[name]
            if self._grad_req[name] == "add":
                tgt._set_data(tgt.handle + g)
            else:
                tgt._set_data(g)

    def _backward_recompute(self, wrt_names, out_grads):
        """Fallback for backward() with no preceding training forward:
        rematerialize the forward from the CURRENT argument values and apply
        the VJP in one jitted computation (the reference's mirror-recompute
        mode)."""
        _, arg_nodes, aux_nodes, _ = self._fwd(True)
        nout = len(self._symbol._heads)
        if len(self.outputs) == nout:
            out_avals = [(tuple(o.shape), o.dtype) for o in self.outputs]
        else:
            _, out_s, _, _, out_t, _ = self._symbol._infer(
                {n.name: tuple(self.arg_dict[n.name].shape)
                 for n in arg_nodes},
                {n.name: self.arg_dict[n.name].dtype for n in arg_nodes},
                partial=True)
            out_avals = list(zip([tuple(s) for s in out_s], out_t))
        heads = self._head_cotangents(out_grads, out_avals)
        # dtypes are part of the key: a second backward() whose out_grads
        # carry different dtypes must not silently reuse the stale entry
        key = (tuple(wrt_names), tuple(str(h.dtype) for h in heads))
        cached = self._bwd_cache.get(key)
        if cached is None:
            run, arg_nodes_b, _, _ = _graph_runner(self._symbol, True)
            arg_names_all = [n.name for n in arg_nodes_b]
            wrt_idx = [arg_names_all.index(n) for n in wrt_names]

            def bwd(arg_vals, aux_vals, rng_key, head_grads):
                sel = tuple(arg_vals[i] for i in wrt_idx)

                def fn(sel_vals):
                    vals = list(arg_vals)
                    for i, v in zip(wrt_idx, sel_vals):
                        vals[i] = v
                    outs, _ = run(tuple(vals), aux_vals, rng_key)
                    return outs

                outs, vjp = jax.vjp(fn, sel)
                cot = tuple(
                    g if g.dtype == o.dtype else g.astype(o.dtype)
                    for o, g in zip(outs, head_grads))
                (grads,) = vjp(cot)
                return grads

            cached = jax.jit(bwd)
            self._bwd_cache[key] = cached
        arg_vals = tuple(self.arg_dict[n.name].handle for n in arg_nodes)
        aux_vals = tuple(self.aux_dict[n.name].handle for n in aux_nodes)
        return cached(arg_vals, aux_vals, self._current_key(), heads)

    # -- misc API parity ----------------------------------------------------
    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(
                    v.handle if isinstance(v, NDArray) else jnp.asarray(v))
            elif not allow_extra_params:
                raise MXNetError(f"copy_params_from: unknown param {k}")
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._set_data(
                    v.handle if isinstance(v, NDArray) else jnp.asarray(v))
            elif not allow_extra_params:
                raise MXNetError(f"copy_params_from: unknown aux {k}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        shapes = {n: tuple(a.shape) for n, a in self.arg_dict.items()}
        shapes.update({k: tuple(v) for k, v in kwargs.items()})
        arg_s, _, aux_s, arg_t, _, aux_t = self._symbol._infer(
            shapes, {}, partial=False)
        arg_dict = {}
        for name, s, t in zip(self._symbol.list_arguments(), arg_s, arg_t):
            old = self.arg_dict[name]
            arg_dict[name] = old if tuple(old.shape) == s \
                else nd.zeros(s, dtype=t)
        aux_dict = {}
        for name, s, t in zip(self._symbol.list_auxiliary_states(), aux_s,
                              aux_t):
            old = self.aux_dict[name]
            aux_dict[name] = old if tuple(old.shape) == s \
                else nd.zeros(s, dtype=t)
        grad_dict = {}
        for name, g in self.grad_dict.items():
            if g is None or name not in arg_dict:
                grad_dict[name] = g
            elif tuple(g.shape) == tuple(arg_dict[name].shape):
                grad_dict[name] = g
            else:
                grad_dict[name] = nd.zeros(arg_dict[name].shape,
                                           dtype=arg_dict[name].dtype)
        return Executor(self._symbol, self._ctx, arg_dict,
                        grad_dict, dict(self._grad_req), aux_dict)

    def __repr__(self):
        return f"<Executor {self._symbol!r} ctx={self._ctx}>"
