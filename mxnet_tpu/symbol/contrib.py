"""mx.sym.contrib — symbolic contrib op namespace (reference
python/mxnet/symbol/contrib.py): every `_contrib_*` registered op without the
prefix, composed symbolically like the rest of `mx.sym`.
"""
from __future__ import annotations

from ..base import MXNetError
from ..ops.registry import get_op as _get_op


def __getattr__(name):
    from . import _make_symbol_function
    for cand in (f"_contrib_{name}", name):
        try:
            op = _get_op(cand)
        except MXNetError:
            continue
        fn = _make_symbol_function(op)
        globals()[name] = fn
        return fn
    raise AttributeError(
        f"module 'mxnet_tpu.symbol.contrib' has no attribute '{name}'")


def rand_zipfian(true_classes, num_sampled, range_max):
    """Symbolic log-uniform candidate sampler (reference
    symbol/contrib.py rand_zipfian) — same math as the nd version, built
    from sym ops so the sampling runs inside the compiled graph (the RNG
    key rides the executor's per-forward split)."""
    import math as _math
    from . import random as _random
    from . import (exp as _exp, floor as _floor, Cast as _cast,
                   _mod_scalar, log as _log, _plus_scalar,
                   _mul_scalar, elemwise_div)

    log_range = _math.log(range_max + 1)
    rand = _random.uniform(0, log_range, shape=(num_sampled,))
    sampled = _cast(_mod_scalar(_floor(_exp(rand) - 1.0),
                                scalar=range_max), dtype="int32")

    def _expected(cls_float):
        ratio = elemwise_div(_plus_scalar(cls_float, scalar=2.0),
                             _plus_scalar(cls_float, scalar=1.0))
        return _mul_scalar(_log(ratio), scalar=num_sampled / log_range)

    expected_true = _expected(_cast(true_classes, dtype="float32"))
    expected_sampled = _expected(_cast(sampled, dtype="float32"))
    return sampled, expected_true, expected_sampled
