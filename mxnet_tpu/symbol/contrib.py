"""mx.sym.contrib — symbolic contrib op namespace (reference
python/mxnet/symbol/contrib.py): every `_contrib_*` registered op without the
prefix, composed symbolically like the rest of `mx.sym`.
"""
from __future__ import annotations

from ..base import MXNetError
from ..ops.registry import get_op as _get_op


def __getattr__(name):
    from . import _make_symbol_function
    for cand in (f"_contrib_{name}", name):
        try:
            op = _get_op(cand)
        except MXNetError:
            continue
        fn = _make_symbol_function(op)
        globals()[name] = fn
        return fn
    raise AttributeError(
        f"module 'mxnet_tpu.symbol.contrib' has no attribute '{name}'")
