"""Symbol: the declarative graph API (``mx.sym``).

Reference: python/mxnet/symbol/symbol.py (Symbol class :55, infer_shape :1045,
simple_bind :1504, bind :1806, tojson/save :1336-1369) + the NNVM graph it
wraps. TPU-native redesign: a Symbol is a lightweight Python DAG over the SAME
registered pure-jax operators the imperative API uses; ``bind`` lowers the DAG
to one jitted XLA computation (the reference lowers to a GraphExecutor with
memory planning — XLA does that planning for us, SURVEY.md §7).

Shape/type inference (reference src/executor/infer_graph_attr_pass.cc) is a
single forward topological sweep: per-op *weight rules* fill in learnable-input
shapes (the only place the reference's backward-inference matters in practice),
then ``jax.eval_shape`` on the op's jax function yields output shapes+dtypes
simultaneously — no separate FInferShape/FInferType fixpoint needed.
"""
from __future__ import annotations

import ast
import json
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError, default_dtype
from ..ops.registry import Op, all_ops, get_op

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "fromjson"]


# ---------------------------------------------------------------------------
# Graph nodes
# ---------------------------------------------------------------------------

class _Node:
    """One vertex of the symbolic DAG: a variable or an op application."""

    __slots__ = ("kind", "name", "op", "params", "inputs", "attrs",
                 "num_outputs")

    def __init__(self, kind: str, name: str, op: Optional[Op] = None,
                 params: Optional[Dict[str, Any]] = None,
                 inputs: Optional[List[Tuple["_Node", int]]] = None,
                 attrs: Optional[Dict[str, str]] = None):
        self.kind = kind              # 'var' | 'op'
        self.name = name
        self.op = op
        self.params = params or {}
        self.inputs = inputs or []
        self.attrs = attrs or {}
        self.num_outputs: Optional[int] = 1 if kind == "var" else None

    def is_rng(self) -> bool:
        return self.attrs.get("__rng__") == "True"

    def is_aux(self) -> bool:
        return self.attrs.get("__aux__") == "True"


class _SymNameManager:
    _lock = threading.Lock()
    _counters: Dict[str, int] = {}

    @classmethod
    def fresh(cls, hint: str) -> str:
        with cls._lock:
            i = cls._counters.get(hint, 0)
            cls._counters[hint] = i + 1
        return f"{hint}{i}"


# ---------------------------------------------------------------------------
# Weight-shape rules: learnable-input inference (the practical subset of the
# reference's bidirectional shape inference). Each rule maps
# (params, input_shapes_by_argname) -> {argname: shape} for still-unknown args.
# ---------------------------------------------------------------------------

def _tup(v, n=None):
    if v is None:
        return None
    t = tuple(int(x) for x in v) if isinstance(v, (tuple, list)) else (int(v),)
    return t


def _rule_fully_connected(p, shp):
    d = shp.get("data")
    if d is None:
        return {}
    units = int(_np.prod(d[1:])) if p.get("flatten", True) else d[-1]
    nh = int(p["num_hidden"])
    return {"weight": (nh, units), "bias": (nh,)}


def _rule_convolution(p, shp):
    d = shp.get("data")
    if d is None:
        return {}
    k = _tup(p["kernel"])
    nf, ng = int(p["num_filter"]), int(p.get("num_group", 1))
    return {"weight": (nf, d[1] // ng) + k, "bias": (nf,)}


def _rule_deconvolution(p, shp):
    d = shp.get("data")
    if d is None:
        return {}
    k = _tup(p["kernel"])
    nf, ng = int(p["num_filter"]), int(p.get("num_group", 1))
    return {"weight": (d[1], nf // ng) + k, "bias": (nf,)}


def _rule_channel_stats(p, shp):
    d = shp.get("data")
    if d is None:
        return {}
    ax = int(p.get("axis", 1)) % len(d)
    c = (d[ax],)
    return {"gamma": c, "beta": c, "moving_mean": c, "moving_var": c}


def _rule_layer_norm(p, shp):
    d = shp.get("data")
    if d is None:
        return {}
    ax = int(p.get("axis", -1)) % len(d)
    return {"gamma": (d[ax],), "beta": (d[ax],)}


def _rule_instance_norm(p, shp):
    d = shp.get("data")
    if d is None:
        return {}
    return {"gamma": (d[1],), "beta": (d[1],)}


def _rule_embedding(p, shp):
    return {"weight": (int(p["input_dim"]), int(p["output_dim"]))}


def _rule_rnn(p, shp):
    from ..ops.nn import rnn_param_size
    d = shp.get("data")
    if d is None:
        return {}
    mode = p["mode"]
    nl = int(p.get("num_layers", 1))
    ss = int(p["state_size"])
    bidir = bool(p.get("bidirectional", False))
    total = nl * (2 if bidir else 1)
    out = {
        "parameters": (rnn_param_size(mode, nl, d[2], ss, bidir),),
        "state": (total, d[1], ss),
    }
    if mode == "lstm":
        out["state_cell"] = (total, d[1], ss)
    return out


def _rule_label_like_batch(p, shp):
    d = shp.get("data")
    if d is None:
        return {}
    return {"label": tuple(d[:-1])}


def _rule_label_like_data(p, shp):
    d = shp.get("data")
    if d is None:
        return {}
    return {"label": tuple(d)}


_WEIGHT_RULES = {
    "FullyConnected": _rule_fully_connected,
    "Convolution": _rule_convolution,
    "Deconvolution": _rule_deconvolution,
    "BatchNorm": _rule_channel_stats,
    "GroupNorm": _rule_instance_norm,  # gamma/beta are (C,) on channel axis 1
    "LayerNorm": _rule_layer_norm,
    "InstanceNorm": _rule_instance_norm,
    "Embedding": _rule_embedding,
    "RNN": _rule_rnn,
    "SoftmaxOutput": _rule_label_like_batch,
    "Softmax": _rule_label_like_batch,
    "LinearRegressionOutput": _rule_label_like_data,
    "MAERegressionOutput": _rule_label_like_data,
    "LogisticRegressionOutput": _rule_label_like_data,
}

# ops whose listed arg names are auxiliary states, not learnable arguments
_AUX_ARGS = {"BatchNorm": ("moving_mean", "moving_var")}



# ---------------------------------------------------------------------------
# Symbol
# ---------------------------------------------------------------------------

class Symbol:
    """An output list over the symbolic DAG (single symbol == one output)."""

    __slots__ = ("_heads", "_selected")

    def __init__(self, heads: List[Tuple[_Node, int]], selected: bool = False):
        self._heads = heads
        # True when this Symbol came from an explicit output selection
        # (sym[i]) — it then has exactly ONE output even if the underlying
        # node is multi-output, so iteration/len must not re-expand it
        self._selected = selected

    # -- identity ------------------------------------------------------------
    @property
    def name(self) -> Optional[str]:
        if len(self._heads) == 1:
            return self._heads[0][0].name
        return None

    def __repr__(self):
        if len(self._heads) == 1:
            return f"<Symbol {self.name}>"
        return f"<Symbol group [{', '.join(n.name for n, _ in self._heads)}]>"

    def __iter__(self):
        # a single fresh multi-output node unpacks into its outputs, so
        # `out, mean, var = F.BatchNorm(...)` works in symbolic traces;
        # an explicitly selected output (sym[i]) never re-expands
        if len(self._heads) == 1 and not self._selected:
            node, cur = self._heads[0]
            if node.kind != "var" and cur == 0 and _num_outputs(node) > 1:
                return (Symbol([(node, i)], selected=True)
                        for i in range(_num_outputs(node)))
        return (Symbol([h], selected=True) for h in self._heads)

    def __len__(self):
        if len(self._heads) == 1 and not self._selected:
            node, cur = self._heads[0]
            if node.kind != "var" and cur == 0:
                return max(_num_outputs(node), 1)
        return len(self._heads)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError(f"no output named {index!r}")
            index = names.index(index)
        if isinstance(index, int):
            if len(self._heads) > 1:
                if not 0 <= index < len(self._heads):
                    raise MXNetError(
                        f"output index {index} out of range "
                        f"({len(self._heads)} outputs)")
                return Symbol([self._heads[index]], selected=True)
            node, cur = self._heads[0]
            if cur != 0:
                # already an explicit output selection: it has ONE output
                if index != 0:
                    raise MXNetError(
                        f"output index {index} out of range (1 output)")
                return Symbol([(node, cur)], selected=True)
            nout = _num_outputs(node)
            if not 0 <= index < nout:
                raise MXNetError(
                    f"output index {index} out of range for {node.name} "
                    f"({nout} outputs)")
            return Symbol([(node, index)], selected=True)
        raise TypeError(index)

    # -- graph walking -------------------------------------------------------
    def _topo(self) -> List[_Node]:
        seen: Dict[int, _Node] = {}
        order: List[_Node] = []

        def visit(node: _Node):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for inp, _ in node.inputs:
                visit(inp)
            order.append(node)

        for n, _ in self._heads:
            visit(n)
        return order

    def list_arguments(self) -> List[str]:
        return [n.name for n in self._topo()
                if n.kind == "var" and not n.is_aux() and not n.is_rng()]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in self._topo() if n.kind == "var" and n.is_aux()]

    def _rng_vars(self) -> List[_Node]:
        return [n for n in self._topo() if n.kind == "var" and n.is_rng()]

    def list_outputs(self) -> List[str]:
        outs = []
        for node, idx in self._heads:
            if node.kind == "var":
                outs.append(node.name)
            elif _num_outputs(node) == 1:
                outs.append(f"{node.name}_output")
            else:
                outs.append(f"{node.name}_output{idx}")
        return outs

    def list_inputs(self) -> List[str]:
        return [n.name for n in self._topo() if n.kind == "var" and not n.is_rng()]

    def get_internals(self) -> "Symbol":
        heads = []
        for node in self._topo():
            for i in range(_num_outputs(node) or 1):
                heads.append((node, i))
        return Symbol(heads)

    def get_children(self) -> Optional["Symbol"]:
        node, _ = self._heads[0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # -- attrs ---------------------------------------------------------------
    def attr(self, key: str) -> Optional[str]:
        return self._heads[0][0].attrs.get(key)

    def list_attr(self) -> Dict[str, str]:
        return dict(self._heads[0][0].attrs)

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        out = {}
        for n in self._topo():
            d = dict(n.attrs)
            if n.kind == "op":
                d.update({k: _attr_str(v) for k, v in n.params.items()})
            if d:
                out[n.name] = d
        return out

    def _set_attr(self, **kwargs):
        self._heads[0][0].attrs.update({k: str(v) for k, v in kwargs.items()})

    # -- inference -----------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_s, out_s, aux_s, _, _, _ = self._infer(
            self._shape_kwargs(args, kwargs), {}, partial=False)
        return arg_s, out_s, aux_s

    def infer_shape_partial(self, *args, **kwargs):
        arg_s, out_s, aux_s, _, _, _ = self._infer(
            self._shape_kwargs(args, kwargs), {}, partial=True)
        return arg_s, out_s, aux_s

    def infer_type(self, *args, **kwargs):
        dtypes = {}
        if args:
            for name, t in zip(self.list_arguments(), args):
                if t is not None:
                    dtypes[name] = _np.dtype(t)
        for k, v in kwargs.items():
            dtypes[k] = _np.dtype(v)
        _, _, _, arg_t, out_t, aux_t = self._infer({}, dtypes, partial=True)
        return arg_t, out_t, aux_t

    def _shape_kwargs(self, args, kwargs) -> Dict[str, Tuple[int, ...]]:
        shapes: Dict[str, Tuple[int, ...]] = {}
        if args:
            for name, s in zip(self.list_arguments(), args):
                if s is not None:
                    shapes[name] = tuple(s)
        for k, v in kwargs.items():
            shapes[k] = tuple(v)
        return shapes

    def _infer(self, shapes: Dict[str, Tuple[int, ...]],
               dtypes: Dict[str, Any], partial: bool):
        """Single forward sweep; returns (arg_shapes, out_shapes, aux_shapes,
        arg_dtypes, out_dtypes, aux_dtypes) aligned with list_arguments /
        list_outputs / list_auxiliary_states."""
        topo = self._topo()
        info: Dict[int, Optional[List[Tuple[Tuple[int, ...], Any]]]] = {}

        def var_info(node: _Node):
            shape = shapes.get(node.name)
            if shape is None and "__shape__" in node.attrs:
                shape = ast.literal_eval(node.attrs["__shape__"])
            dt = dtypes.get(node.name)
            if dt is None and "__dtype__" in node.attrs:
                dt = _np.dtype(node.attrs["__dtype__"])
            if dt is None:
                dt = _np.dtype("uint32" if node.is_rng() else default_dtype())
            if node.is_rng() and shape is None:
                shape = (2,)
            return shape, dt

        for node in topo:
            if node.kind == "var":
                s, d = var_info(node)
                info[id(node)] = [(tuple(s) if s is not None else None, d)]
                continue
            # try weight rules for unknown var inputs
            rule = _WEIGHT_RULES.get(node.op.name)
            argnames = _op_arg_names(node.op)
            in_info = []
            by_name = {}
            for i, (inp, oi) in enumerate(node.inputs):
                cell = info.get(id(inp))
                sh = cell[oi][0] if cell and oi < len(cell) and cell[oi] else None
                nm = argnames[i] if i < len(argnames) else f"arg{i}"
                by_name[nm] = sh
            if rule is not None:
                try:
                    fills = rule(node.params, by_name)
                except Exception:
                    fills = {}
                for i, (inp, oi) in enumerate(node.inputs):
                    nm = argnames[i] if i < len(argnames) else f"arg{i}"
                    if inp.kind == "var" and by_name.get(nm) is None \
                            and nm in fills:
                        cell = info[id(inp)]
                        dt = cell[oi][1]
                        info[id(inp)] = [(tuple(fills[nm]), dt)]
                        by_name[nm] = tuple(fills[nm])
            unknown = False
            structs = []
            for i, (inp, oi) in enumerate(node.inputs):
                cell = info[id(inp)]
                sh, dt = cell[oi] if oi < len(cell) else (None, None)
                if sh is None:
                    unknown = True
                    break
                structs.append(jax.ShapeDtypeStruct(sh, dt))
            if unknown:
                if not partial:
                    raise MXNetError(
                        f"infer_shape: cannot infer input shapes of node "
                        f"'{node.name}' (op {node.op.name}); provide shapes "
                        f"for its variables")
                info[id(node)] = [(None, _np.dtype(default_dtype()))] * \
                    max(_num_outputs(node), 1)
                continue
            params = _resolved_params(node)
            try:
                out = jax.eval_shape(node.op.unbound(params), *structs)
            except Exception as e:  # noqa: BLE001
                raise MXNetError(
                    f"infer_shape failed at node '{node.name}' "
                    f"(op {node.op.name}): {e}") from None
            outs = out if isinstance(out, tuple) else (out,)
            node.num_outputs = len(outs)
            info[id(node)] = [(tuple(o.shape), _np.dtype(o.dtype)) for o in outs]

        def collect(names_nodes):
            sh, dt = [], []
            for n in names_nodes:
                cell = info.get(id(n))
                s, d = cell[0] if cell else (None, None)
                sh.append(s)
                dt.append(d)
            return sh, dt

        arg_nodes = [n for n in topo if n.kind == "var" and not n.is_aux()
                     and not n.is_rng()]
        aux_nodes = [n for n in topo if n.kind == "var" and n.is_aux()]
        arg_s, arg_t = collect(arg_nodes)
        aux_s, aux_t = collect(aux_nodes)
        out_s, out_t = [], []
        for node, idx in self._heads:
            cell = info.get(id(node))
            s, d = cell[idx] if cell and idx < len(cell) else (None, None)
            out_s.append(s)
            out_t.append(d)
        return arg_s, out_s, aux_s, arg_t, out_t, aux_t

    # -- serialization -------------------------------------------------------
    def tojson(self) -> str:
        topo = self._topo()
        nid = {id(n): i for i, n in enumerate(topo)}
        nodes = []
        for n in topo:
            if n.kind == "var":
                entry = {"op": "null", "name": n.name, "inputs": []}
                if n.attrs:
                    entry["attrs"] = dict(n.attrs)
            else:
                entry = {
                    "op": n.op.name,
                    "name": n.name,
                    "attrs": {k: _attr_str(v) for k, v in n.params.items()
                              if v is not None},
                    "inputs": [[nid[id(i)], oi, 0] for i, oi in n.inputs],
                }
                if n.num_outputs is not None and n.num_outputs != 1:
                    entry["num_outputs"] = n.num_outputs
                if n.attrs:
                    entry["attrs"].update(n.attrs)
            nodes.append(entry)
        return json.dumps({
            "nodes": nodes,
            "arg_nodes": [i for i, n in enumerate(topo) if n.kind == "var"],
            "heads": [[nid[id(n)], oi, 0] for n, oi in self._heads],
            "attrs": {"mxnet_version": ["int", 20000]},
        }, indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- composition helpers -------------------------------------------------
    def __copy__(self):
        return Symbol(list(self._heads))

    def __deepcopy__(self, memo):
        # graph nodes are immutable-after-construction; sharing is fine
        return Symbol(list(self._heads))

    # -- binding / eval (implemented in executor.py, attached below) ---------
    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import Executor
        return Executor._bind(self, ctx, args, args_grad, grad_req, aux_states)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from .executor import Executor
        return Executor._simple_bind(self, ctx, grad_req, type_dict, kwargs)

    def eval(self, ctx=None, **kwargs):
        from .executor import Executor
        args = {k: v for k, v in kwargs.items()}
        ex = Executor._bind(self, ctx, args, None, "null", None)
        return ex.forward(is_train=False)

    # hybrid-friendly: calling a symbol on other symbols re-binds its free
    # variables (reference Symbol.__call__ composition)
    def __call__(self, *args, **kwargs):
        mapping: Dict[str, Symbol] = {}
        names = self.list_arguments()
        for n, s in zip(names, args):
            mapping[n] = s
        mapping.update(kwargs)
        for v in mapping.values():
            if not isinstance(v, Symbol):
                raise TypeError("Symbol composition requires Symbols")
        return self._substitute(mapping)

    def _substitute(self, mapping: Dict[str, "Symbol"]) -> "Symbol":
        memo: Dict[int, _Node] = {}

        def clone(node: _Node) -> _Node:
            if id(node) in memo:
                return memo[id(node)]
            if node.kind == "var":
                if node.name in mapping:
                    rep, ridx = mapping[node.name]._heads[0]
                    if ridx != 0:
                        raise MXNetError("cannot substitute multi-output head")
                    memo[id(node)] = rep
                    return rep
                memo[id(node)] = node
                return node
            new = _Node("op", node.name, node.op, dict(node.params),
                        [(clone(i), oi) for i, oi in node.inputs],
                        dict(node.attrs))
            new.num_outputs = node.num_outputs
            memo[id(node)] = new
            return new

        return Symbol([(clone(n), oi) for n, oi in self._heads])


# static output-arity rules for multi-output ops (arity depends only on
# params, so it is known at composition time — no inference pass needed)
_NUM_OUTPUT_RULES = {
    "BatchNorm": lambda p: 3,
    "moments": lambda p: 2,
    "SliceChannel": lambda p: int(p.get("num_outputs", 1)),
    "split_v2": lambda p: (len(p["indices_or_sections"]) + 1
                           if isinstance(p.get("indices_or_sections"),
                                         (tuple, list))
                           else int(p.get("indices_or_sections", 1))),
    "topk": lambda p: 2 if p.get("ret_typ", "indices") == "both" else 1,
    "linalg_gelqf": lambda p: 2,
    "linalg_slogdet": lambda p: 2,
    "RNN": lambda p: ((3 if p.get("mode") == "lstm" else 2)
                      if p.get("state_outputs", False) else 1),
}


def _num_outputs(node: _Node) -> int:
    if node.num_outputs is not None:
        return node.num_outputs
    if node.kind == "var" or not node.op.multi_output:
        node.num_outputs = 1
        return 1
    rule = _NUM_OUTPUT_RULES.get(node.op.name)
    if rule is not None:
        try:
            node.num_outputs = int(rule(node.params))
        except Exception:
            return 1
    return node.num_outputs or 1


def _attr_str(v) -> str:
    if isinstance(v, (list, tuple)):
        return str(tuple(v))
    return str(v)


def _parse_attr(s: str):
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


# ---------------------------------------------------------------------------
# op arg-name introspection (cached)
# ---------------------------------------------------------------------------

_ARG_NAMES_CACHE: Dict[str, Tuple[Tuple[str, bool], ...]] = {}


def _op_arg_spec(op: Op) -> Tuple[Tuple[str, bool], ...]:
    """[(argname, required)] for the op's array inputs, from its signature."""
    import inspect
    cached = _ARG_NAMES_CACHE.get(op.name)
    if cached is not None:
        return cached
    spec = []
    try:
        sig = inspect.signature(op.fn)
        for p in sig.parameters.values():
            # POSITIONAL_ONLY too: jnp ufunc-style fns are `(x1, x2, /)`
            # (jnp.divide et al.) — missing them dropped the op's inputs
            if p.kind in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY):
                spec.append((p.name, p.default is p.empty))
            elif p.kind == p.VAR_POSITIONAL:
                spec.append(("*" + p.name, False))
            else:
                break
    except (TypeError, ValueError):
        pass
    out = tuple(spec)
    _ARG_NAMES_CACHE[op.name] = out
    return out


def _op_arg_names(op: Op) -> List[str]:
    return [n.lstrip("*") for n, _ in _op_arg_spec(op)]


_PARAM_NAMES_CACHE: Dict[str, set] = {}


def _op_param_names(op: Op) -> set:
    import inspect
    cached = _PARAM_NAMES_CACHE.get(op.name)
    if cached is not None:
        return cached
    try:
        sig = inspect.signature(op.fn)
        out = {p.name for p in sig.parameters.values()
               if p.kind == p.KEYWORD_ONLY}
    except (TypeError, ValueError):
        out = set()
    _PARAM_NAMES_CACHE[op.name] = out
    return out


def _resolved_params(node: _Node, training: Optional[bool] = None) -> dict:
    params = dict(node.params)
    if training is not None and "training" in _op_param_names(node.op):
        params["training"] = training
    return params


# ---------------------------------------------------------------------------
# Variable / Group / op-node construction
# ---------------------------------------------------------------------------

def Variable(name: str, attr: Optional[dict] = None, shape=None, dtype=None,
             lr_mult=None, wd_mult=None, init=None, stype=None, **kwargs) -> Symbol:
    """Create a symbolic variable (reference symbol.py var())."""
    from ..attribute import current as _attr_scope
    attrs = {str(k): str(v) for k, v in _attr_scope().get(attr or {}).items()}
    if shape is not None:
        attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        attrs["__dtype__"] = str(_np.dtype(dtype))
    if lr_mult is not None:
        attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attrs["__wd_mult__"] = str(wd_mult)
    if init is not None:
        attrs["__init__"] = init if isinstance(init, str) else \
            init.__class__.__name__
    for k, v in kwargs.items():
        attrs[k] = str(v)
    return Symbol([(_Node("var", name, attrs=attrs), 0)])


var = Variable
v = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    heads: List[Tuple[_Node, int]] = []
    for s in symbols:
        if not isinstance(s, Symbol):
            raise TypeError("Group expects Symbols")
        heads.extend(s._heads)
    return Symbol(heads)


def _apply_op(op: Op, *args, name: Optional[str] = None,
              attr: Optional[dict] = None, **kwargs) -> Symbol:
    """Create an op node; auto-create variables for absent learnable inputs
    (the reference does this in the generated symbol functions)."""
    spec = _op_arg_spec(op)
    # NameManager/Prefix scope (reference python/mxnet/name.py); falls back
    # to the process-global counters when no user scope is active (the
    # bottom-of-stack default manager would restart numbering per thread)
    from ..name import _stack as _name_stack
    if name is None and len(_name_stack()) > 1:
        node_name = _name_stack()[-1].get(None, op.name.lower().lstrip("_"))
    else:
        node_name = name or _SymNameManager.fresh(op.name.lower().lstrip("_"))
    aux_names = _AUX_ARGS.get(op.name, ())

    # collect positional symbol inputs; varargs ops swallow all positionals
    pos = list(args)
    inputs: List[Tuple[_Node, int]] = []
    params: Dict[str, Any] = {}

    def as_head(s, argname):
        if isinstance(s, Symbol):
            if len(s._heads) != 1:
                raise MXNetError(
                    f"op {op.name} input {argname}: expected single-output "
                    "symbol")
            return s._heads[0]
        raise TypeError(
            f"op {op.name} input {argname}: expected Symbol, got {type(s)}")

    consumed = set()
    for i, (argname, required) in enumerate(spec):
        if argname.startswith("*"):
            for j, s in enumerate(pos[i:]):
                inputs.append(as_head(s, f"{argname}[{j}]"))
            consumed.update(range(i, len(pos)))
            break
        val = None
        if i < len(pos):
            val = pos[i]
            consumed.add(i)
        elif argname in kwargs and isinstance(kwargs[argname], Symbol):
            val = kwargs.pop(argname)
        if val is None:
            # optional input elision: bias under no_bias, absent state_cell…
            if not required:
                if argname == "bias" and not kwargs.get("no_bias", False):
                    pass  # create the bias variable
                elif argname == "state_cell" and kwargs.get("mode") == "lstm":
                    pass  # LSTM needs a cell state
                else:
                    continue
            attrs = {}
            if argname in aux_names:
                attrs["__aux__"] = "True"
            if argname == "key":
                attrs["__rng__"] = "True"
            vnode = _Node("var", f"{node_name}_{argname}", attrs=attrs)
            inputs.append((vnode, 0))
        else:
            inputs.append(as_head(val, argname))
    if len(consumed) < len(pos):
        raise MXNetError(f"op {op.name}: too many positional inputs")

    params.update({k: _coerce_param(v) for k, v in kwargs.items()})
    from ..attribute import current as _attr_scope
    attrs = {str(k): str(v) for k, v in _attr_scope().get(attr or {}).items()}
    node = _Node("op", node_name, op, params, inputs, attrs)
    return Symbol([(node, 0)])


def _coerce_param(v):
    if isinstance(v, str):
        parsed = _parse_attr(v)
        if parsed is None:
            return None
        if isinstance(parsed, (int, float, bool, tuple, list)):
            return tuple(parsed) if isinstance(parsed, list) else parsed
        return v
    if isinstance(v, list):
        return tuple(v)
    if isinstance(v, _np.dtype):
        return str(v)
    return v


# ---------------------------------------------------------------------------
# JSON load
# ---------------------------------------------------------------------------

def load_json(json_str: str) -> Symbol:
    g = json.loads(json_str)
    nodes: List[_Node] = []
    for entry in g["nodes"]:
        attrs = {k: str(v) for k, v in entry.get("attrs", entry.get("param", {})).items()}
        if entry["op"] == "null":
            nodes.append(_Node("var", entry["name"], attrs=attrs))
        else:
            op = get_op(entry["op"])
            pnames = _op_param_names(op)
            params = {k: _coerce_param(v) for k, v in attrs.items()
                      if k in pnames}
            extra = {k: v for k, v in attrs.items() if k not in pnames}
            inputs = [(nodes[i], oi) for i, oi, *_ in entry["inputs"]]
            node = _Node("op", entry["name"], op, params, inputs, extra)
            if "num_outputs" in entry:
                node.num_outputs = int(entry["num_outputs"])
            nodes.append(node)
    heads = [(nodes[i], oi) for i, oi, *_ in g["heads"]]
    return Symbol(heads)


fromjson = load_json


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


# ---------------------------------------------------------------------------
# Operator overloads & tensor methods on Symbol
# ---------------------------------------------------------------------------

def _binary(op_name, scalar_op, rscalar_op=None):
    def fwd(self, other):
        if isinstance(other, Symbol):
            return _apply_op(get_op(op_name), self, other)
        return _apply_op(get_op(scalar_op), self, scalar=float(other))

    def rev(self, other):
        if rscalar_op is None:
            return fwd(self, other)
        return _apply_op(get_op(rscalar_op), self, scalar=float(other))

    return fwd, rev


for _name, _ops in {
    "add": ("elemwise_add", "_plus_scalar", None),
    "sub": ("elemwise_sub", "_minus_scalar", "_rminus_scalar"),
    "mul": ("elemwise_mul", "_mul_scalar", None),
    "truediv": ("elemwise_div", "_div_scalar", "_rdiv_scalar"),
    "mod": ("_mod", "_mod_scalar", "_rmod_scalar"),
    "pow": ("_power", "_power_scalar", "_rpower_scalar"),
}.items():
    _f, _r = _binary(*_ops)
    setattr(Symbol, f"__{_name}__", _f)
    setattr(Symbol, f"__r{_name}__", _r)

for _name, _opn, _sopn in [
    ("eq", "_equal", "_equal_scalar"),
    ("ne", "_not_equal", "_not_equal_scalar"),
    ("gt", "_greater", "_greater_scalar"),
    ("ge", "_greater_equal", "_greater_equal_scalar"),
    ("lt", "_lesser", "_lesser_scalar"),
    ("le", "_lesser_equal", "_lesser_equal_scalar"),
]:
    _f, _ = _binary(_opn, _sopn)
    setattr(Symbol, f"__{_name}__", _f)

Symbol.__neg__ = lambda self: _apply_op(get_op("negative"), self)
Symbol.__hash__ = lambda self: id(self._heads[0][0]) ^ self._heads[0][1]


def _method(op_name):
    def m(self, *args, **kwargs):
        return _apply_op(get_op(op_name), self, *args, **kwargs)
    m.__name__ = op_name
    return m


for _meth, _opn in {
    "reshape": "Reshape", "transpose": "transpose", "flatten": "Flatten",
    "sum": "sum", "mean": "mean", "max": "max", "min": "min", "prod": "prod",
    "abs": "abs", "exp": "exp", "log": "log", "sqrt": "sqrt", "square": "square",
    "dot": "dot", "astype": "Cast", "cast": "Cast", "slice": "slice",
    "slice_axis": "slice_axis", "expand_dims": "expand_dims",
    "squeeze": "squeeze", "clip": "clip", "sigmoid": "sigmoid",
    "tanh": "tanh", "relu": "relu", "softmax": "softmax",
    "log_softmax": "log_softmax", "argmax": "argmax", "argmin": "argmin",
    "take": "take", "tile": "tile", "repeat": "repeat", "norm": "norm",
    "round": "round", "rsqrt": "rsqrt", "reciprocal": "reciprocal",
    "one_hot": "one_hot", "broadcast_like": "broadcast_like",
    "diag": "diag", "topk": "topk", "sort": "sort", "argsort": "argsort",
    "split": "split",
}.items():
    try:
        get_op(_opn)
    except MXNetError:
        continue
    setattr(Symbol, _meth, _method(_opn))
