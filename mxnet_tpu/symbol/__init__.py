"""``mx.sym`` / ``mx.symbol`` — the declarative graph namespace.

Reference: python/mxnet/symbol/ — op functions are code-generated at import
from the C op registry (python/mxnet/symbol/register.py). Here they are
generated from the same Python op registry the imperative API uses, so the
two namespaces are always in sync by construction.
"""
from __future__ import annotations

import functools as _functools

from ..base import MXNetError
from ..ops.registry import all_ops as _all_ops, get_op as _get_op
from .symbol import (Symbol, Variable, var, Group, load, load_json, fromjson,
                     _apply_op)
from .executor import Executor

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "fromjson", "Executor", "zeros", "ones", "full", "arange"]


def _make_symbol_function(op):
    def fn(*args, **kwargs):
        name = kwargs.pop("name", None)
        attr = kwargs.pop("attr", None)
        return _apply_op(op, *args, name=name, attr=attr, **kwargs)

    fn.__name__ = op.name
    fn.__doc__ = (op.doc or "") + \
        f"\n\n(symbolic form of operator `{op.name}`)"
    return fn


_seen = set()
for _name, _op in sorted(_all_ops().items()):
    if _name in ("Variable", "Group"):
        continue
    if _name not in _seen:
        globals()[_name] = _make_symbol_function(_op)
        _seen.add(_name)


def __getattr__(name):
    # ops registered after this module imported (e.g. contrib extensions)
    # resolve lazily from the live registry, keeping nd/sym in sync
    if name == "random":
        import importlib
        return importlib.import_module(__name__ + ".random")
    if name == "contrib":
        # importlib, not `from . import`: the latter's hasattr() probe
        # re-enters this __getattr__ before the submodule import starts.
        import importlib
        return importlib.import_module(__name__ + ".contrib")
    try:
        op = _get_op(name)
    except MXNetError:
        raise AttributeError(
            f"module 'mxnet_tpu.symbol' has no attribute '{name}'") from None
    fn = _make_symbol_function(op)
    globals()[name] = fn
    return fn


def zeros(shape, dtype=None, **kwargs):
    return _apply_op(_get_op("_zeros"), shape=tuple(shape)
                     if isinstance(shape, (list, tuple)) else (shape,),
                     dtype=dtype, **kwargs)


def ones(shape, dtype=None, **kwargs):
    return _apply_op(_get_op("_ones"), shape=tuple(shape)
                     if isinstance(shape, (list, tuple)) else (shape,),
                     dtype=dtype, **kwargs)


def full(shape, val, dtype=None, **kwargs):
    return _apply_op(_get_op("_full"), shape=tuple(shape)
                     if isinstance(shape, (list, tuple)) else (shape,),
                     value=float(val), dtype=dtype, **kwargs)


def arange(start, stop=None, step=1.0, repeat=1, dtype=None, **kwargs):
    return _apply_op(_get_op("_arange"), start=start, stop=stop, step=step,
                     repeat=repeat, dtype=dtype, **kwargs)
