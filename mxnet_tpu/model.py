"""model.py — FeedForward (the oldest API) + checkpoint helpers.

Reference: python/mxnet/model.py (FeedForward, save_checkpoint:407,
load_checkpoint:456). FeedForward delegates to Module internally, same as
late reference versions effectively did.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray


def pack_params(arg_params, aux_params):
    """The single definition of the checkpoint key format (arg:/aux:)."""
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    return save_dict


def save_params_file(fname, arg_params, aux_params):
    from .serialization import save_ndarrays
    save_ndarrays(fname, pack_params(arg_params, aux_params))


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """(reference model.py:407)"""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_params_file("%s-%04d.params" % (prefix, epoch), arg_params, aux_params)
    logging.info("Saved checkpoint to \"%s-%04d.params\"", prefix, epoch)


def load_params(fname):
    from .serialization import load_ndarrays
    loaded = load_ndarrays(fname)
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        tp, name = k.split(":", 1) if ":" in k else ("arg", k)
        (arg_params if tp == "arg" else aux_params)[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """(reference model.py:456) -> (symbol, arg_params, aux_params)"""
    from . import symbol as sym_mod
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params("%s-%04d.params" % (prefix, epoch))
    return symbol, arg_params, aux_params


class FeedForward:
    """(reference model.py:546) — kept for API parity; Module is the real
    engine underneath."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self.numpy_batch_size = numpy_batch_size
        self.kwargs = kwargs
        self._module = None

    def _as_iter(self, X, y=None, batch_size=None):
        from .io import NDArrayIter
        if hasattr(X, "provide_data"):
            return X
        return NDArrayIter(X, y, batch_size or self.numpy_batch_size)

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        from .module import Module
        data = self._as_iter(X, y)
        label_names = [n for n in self.symbol.list_arguments()
                       if n.endswith("label")] or ["softmax_label"]
        data_names = [d[0] if isinstance(d, (tuple, list)) else d.name
                      for d in data.provide_data]
        self._module = Module(self.symbol, data_names=data_names,
                              label_names=label_names, context=self.ctx,
                              logger=logger or logging)
        # reference FeedForward forwards plain kwargs (learning_rate,
        # momentum, wd, …) into optimizer creation
        opt_params = dict(self.kwargs.get("optimizer_params",
                                          (("learning_rate", 0.01),)))
        for k, v in self.kwargs.items():
            if k != "optimizer_params":
                opt_params[k] = v
        import contextlib
        from . import telemetry as _telem
        # whole-fit wall time into mx_phase_seconds; the inner epoch loop
        # (BaseModule.fit) reports the per-step metrics
        phase = _telem.timed("fit", "feedforward") if _telem._ENABLED \
            else contextlib.nullcontext()
        with phase:
            self._module.fit(data, eval_data=eval_data,
                             eval_metric=eval_metric,
                             epoch_end_callback=epoch_end_callback,
                             batch_end_callback=batch_end_callback,
                             kvstore=kvstore, optimizer=self.optimizer,
                             optimizer_params=tuple(opt_params.items()),
                             initializer=self.initializer,
                             arg_params=self.arg_params,
                             aux_params=self.aux_params,
                             begin_epoch=self.begin_epoch,
                             num_epoch=self.num_epoch,
                             eval_end_callback=eval_end_callback,
                             eval_batch_end_callback=eval_batch_end_callback,
                             monitor=monitor)
        self.arg_params, self.aux_params = self._module.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        from .module import Module
        from .engine import async_feed as _feed
        # forward-only loops overlap too: stage device-resident batches
        # ahead of the executor (fit gets this inside BaseModule.fit)
        data = _feed.maybe_wrap(self._as_iter(X), name="predict")
        if self._module is None:
            data_names = [d[0] if isinstance(d, (tuple, list)) else d.name
                          for d in data.provide_data]
            self._module = Module(self.symbol, data_names=data_names,
                                  label_names=[], context=self.ctx)
            self._module.bind(data.provide_data, for_training=False)
            self._module.init_params(arg_params=self.arg_params,
                                     aux_params=self.aux_params)
        out = self._module.predict(data, num_batch=num_batch, reset=reset)
        return out.asnumpy() if isinstance(out, NDArray) else out

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        data = self._as_iter(X)
        res = self._module.score(data, eval_metric, num_batch=num_batch,
                                 batch_end_callback=batch_end_callback,
                                 reset=reset)
        return res[0][1] if res else None

    def save(self, prefix, epoch=None, remove_amp_cast=True):
        save_checkpoint(prefix, epoch if epoch is not None else
                        (self.num_epoch or 0), self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None, **kwargs):
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger)
        return model
