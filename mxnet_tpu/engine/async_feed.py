"""Async device feed + bounded in-flight step dispatch.

The reference framework's heart is its asynchronous dependency engine
(src/engine/threaded_engine.h): Python pushes operations and never blocks;
reads/writes are versioned so the device pipeline stays full. On TPU the
XLA runtime already gives us async dispatch per computation — what is
missing is the *loop around the step*: host batch assembly, `device_put`,
and eager loss/metric reads each iteration serialize the pipeline
(arXiv:2301.13062 measures the dispatch-overlap this throws away). This
module is the TPU-native analog of that engine, in three parts:

  - **DeviceFeed** — wraps any ``DataIter`` / gluon ``DataLoader`` /
    iterable of batches, runs one background producer thread, and delivers
    batches already ``jax.device_put`` with the consumer's input sharding
    (replicated, or dp-sharded to match a ``DataParallelTrainer``), so the
    host->device copy of batch i+1 overlaps the compute of batch i. Queue
    depth is ``MXNET_TPU_FEED_DEPTH`` (default 2). The ``device_put`` is
    *explicit*, so ``sanitize.guard()``'s ``transfer_guard("disallow")``
    stays clean in the dispatch path. Batch order is exactly the wrapped
    iterator's order (single producer, FIFO queue), including across
    ``reset()`` and a mid-epoch ``StopIteration``.
  - **DispatchWindow** — the bounded in-flight window: trainers ``admit()``
    each dispatched step's output handle and the window blocks
    (``block_until_ready``) on the (i-K)th step once more than
    ``MXNET_TPU_INFLIGHT_STEPS`` (default 2) are outstanding. Backpressure
    instead of unbounded queueing; ``drain()`` is the epoch/eval-boundary
    sync point.
  - **PendingScalar** — a lazy handle for per-step losses/metrics that stay
    on device: ``float()`` / ``.item()`` / ``.asnumpy()`` sync on *read*,
    so a fit loop can collect losses without a host round-trip per step and
    drain them at the boundary.

Telemetry (only while ``mx.telemetry`` is enabled): the feed exports
``mx_feed_queue_depth`` and ``mx_feed_stall_seconds_total`` (consumer time
spent waiting on an empty queue — nonzero stall means the producer, not
the device, is the bottleneck), and the window exports
``mx_inflight_steps``. Step timing in the trainers is recorded *after*
window admission, i.e. at completion pace under backpressure, so
instrumentation never re-serializes the pipeline (docs/input_pipeline.md).
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

import numpy as _np

from ..base import MXNetError, env
from ..telemetry import tracing as _tracing

__all__ = ["DeviceFeed", "DispatchWindow", "PendingScalar", "drain",
           "feed_depth", "inflight_steps", "maybe_wrap"]

env.declare("MXNET_TPU_FEED_DEPTH", 2, int,
            "DeviceFeed prefetch queue depth (batches staged on device "
            "ahead of the consumer); 0 disables the async feed wrap in "
            "fit loops")
env.declare("MXNET_TPU_INFLIGHT_STEPS", 2, int,
            "Max dispatched-but-incomplete training steps before the "
            "trainer blocks on the oldest one (0 = fully synchronous)")
env.declare("MXNET_TPU_FEED_GIL_INTERVAL", 0.001, float,
            "sys.setswitchinterval applied when a DeviceFeed producer "
            "starts (never raised, only lowered): the default 5 ms GIL "
            "switch interval makes the consumer wait up to 5 ms behind a "
            "producer mid-batch on few-core hosts; 0 leaves the "
            "interpreter setting untouched")
env.declare("MXNET_TPU_FEED_RESTARTS", 0, int,
            "Opt-in supervised DeviceFeed: bounded producer restarts on "
            "transient source errors (OSError/ConnectionError/"
            "TimeoutError + injected faults) — the producer re-opens the "
            "source iterator, fast-forwards past already-delivered "
            "batches host-side, and resumes; 0 (default) surfaces the "
            "first error at next()")
env.declare("MXNET_TPU_FEED_JOIN_TIMEOUT", 5.0, float,
            "Seconds to wait (per drain round, two rounds) for a "
            "DeviceFeed producer thread to exit at stop/reset/close "
            "before abandoning it (warned loudly + counted in "
            "mx_feed_producer_leaks_total)")


def feed_depth() -> int:
    return int(env.get("MXNET_TPU_FEED_DEPTH"))


def inflight_steps() -> int:
    return int(env.get("MXNET_TPU_INFLIGHT_STEPS"))


# ---------------------------------------------------------------------------
# Lazy scalar handles
# ---------------------------------------------------------------------------

def _raw_of(v):
    """Unwrap NDArray/PendingScalar to the underlying jax.Array."""
    if isinstance(v, PendingScalar):
        return v._raw
    data = getattr(v, "_data", None)
    return data if data is not None and hasattr(data, "block_until_ready") \
        else v


class PendingScalar:
    """A device-resident scalar (a step's loss/metric) that syncs lazily.

    Returned by the fused trainers' ``step()``: holding it costs nothing;
    ``float()`` / ``.item()`` / ``.asnumpy()`` / ``np.asarray`` block on the
    value. ``repr()`` deliberately does NOT sync, so logging a handle does
    not serialize the pipeline — read it at a drain point instead.
    """

    __slots__ = ("_raw",)

    def __init__(self, raw):
        self._raw = _raw_of(raw)

    @property
    def raw(self):
        """The underlying device array (no sync)."""
        return self._raw

    def value(self):
        return self._raw

    def block_until_ready(self):
        if hasattr(self._raw, "block_until_ready"):
            self._raw.block_until_ready()
        return self

    def __float__(self):
        v = float(self._raw)
        if _tracing._ENABLED:
            # nonfinite-loss watchdog rides the sync the caller asked for
            _tracing.check_loss(v, source="pending_scalar")
        return v

    def item(self):
        return self.__float__()

    def asnumpy(self):
        return _np.asarray(self._raw)

    def __array__(self, dtype=None):
        a = _np.asarray(self._raw)
        return a.astype(dtype) if dtype is not None else a

    @property
    def shape(self):
        return tuple(getattr(self._raw, "shape", ()))

    @property
    def dtype(self):
        return getattr(self._raw, "dtype", None)

    def __repr__(self):
        return (f"PendingScalar(shape={self.shape}, dtype={self.dtype}, "
                "pending)")


def drain(values):
    """Block on a (possibly nested) collection of pending step outputs and
    return the scalar values as floats where they are 0-d. The designated
    epoch/eval-boundary sync point for a loop that collected
    ``PendingScalar`` handles."""
    if isinstance(values, (list, tuple)):
        return type(values)(drain(v) for v in values)
    raw = _raw_of(values)
    if hasattr(raw, "block_until_ready"):
        raw.block_until_ready()
    if getattr(raw, "ndim", None) == 0 or isinstance(values, PendingScalar):
        v = float(raw)
        if _tracing._ENABLED:
            _tracing.check_loss(v, source="drain")
        return v
    return raw


# ---------------------------------------------------------------------------
# Bounded in-flight dispatch window
# ---------------------------------------------------------------------------

class DispatchWindow:
    """Backpressure for async step dispatch: keep at most ``depth`` steps in
    flight; ``admit()`` the newly dispatched step's output handle and block
    on the (i-depth)th step's outputs once the window is full — the
    TPU-native equivalent of the reference engine's bounded pending-op
    queue. ``depth=0`` degrades to a fully synchronous loop (every admit
    blocks immediately); depth defaults to ``MXNET_TPU_INFLIGHT_STEPS``.
    """

    def __init__(self, depth: Optional[int] = None, name: str = "step"):
        self.depth = inflight_steps() if depth is None else int(depth)
        self.name = name
        self._pending: "deque[Any]" = deque()
        self.retired = 0
        self.wait_seconds = 0.0
        self.max_inflight = 0

    def __len__(self):
        return len(self._pending)

    @staticmethod
    def _block(handles):
        if isinstance(handles, (list, tuple)):
            for h in handles:
                DispatchWindow._block(h)
            return
        raw = _raw_of(handles)
        if hasattr(raw, "block_until_ready"):
            raw.block_until_ready()

    def admit(self, handles):
        """Register one dispatched step; blocks on the oldest in-flight step
        when the window exceeds its depth (never on the current one)."""
        self._pending.append(handles)
        wait0 = self.wait_seconds
        retired = 0
        t_first = 0.0
        while len(self._pending) > max(self.depth, 0):
            old = self._pending.popleft()
            t0 = time.perf_counter()
            if retired == 0:
                t_first = t0
            self._block(old)
            self.wait_seconds += time.perf_counter() - t0
            self.retired += 1
            retired += 1
        self.max_inflight = max(self.max_inflight, len(self._pending))
        if _tracing._ENABLED and retired:
            # the backpressure wait, rebuilt from the stamps the window
            # already took — no clock reads beyond the existing ones
            _tracing.record_span("mx.window.admit", t_first,
                                 t_first + (self.wait_seconds - wait0),
                                 source=self.name, retired=retired,
                                 inflight=len(self._pending))
        from .. import telemetry as _telem
        if _telem._ENABLED:
            _telem.record_inflight(len(self._pending), source=self.name)
            # cumulative block time for the goodput waterfall's
            # dispatch_backpressure lane — the float this window already
            # accumulated, no extra clock read
            _telem.record_dispatch_wait(self.wait_seconds, source=self.name)

    def drain(self):
        """Block until every admitted step completed (epoch/eval boundary)."""
        t_d0 = time.perf_counter() if _tracing._ENABLED else 0.0
        drained = 0
        while self._pending:
            old = self._pending.popleft()
            t0 = time.perf_counter()
            self._block(old)
            self.wait_seconds += time.perf_counter() - t0
            self.retired += 1
            drained += 1
        if _tracing._ENABLED:
            _tracing.record_span("mx.window.drain", t_d0,
                                 time.perf_counter(), source=self.name,
                                 drained=drained)
        from .. import telemetry as _telem
        if _telem._ENABLED:
            _telem.record_inflight(0, source=self.name)
            _telem.record_dispatch_wait(self.wait_seconds, source=self.name)


# ---------------------------------------------------------------------------
# Sharding-aware background device feed
# ---------------------------------------------------------------------------

_END = object()


def _bounded_put(q: "queue.Queue", item, stop: threading.Event) -> bool:
    """put() that gives up when the consumer asked the producer to stop —
    a blocking put into a full queue with a departed consumer is exactly
    the thread leak the reference prefetcher's shutdown path avoids."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


class DeviceFeed:
    """Wrap a batch source; deliver batches already placed on device.

    ``source`` may be a ``DataIter`` (``next()``/``reset()``/
    ``provide_data``), a gluon ``DataLoader``, or any re-iterable of
    batches. Each yielded item keeps its structure (``DataBatch`` fields,
    tuples, single arrays) with every array leaf explicitly
    ``jax.device_put`` by the producer thread:

      - with ``mesh``+``data_spec`` (what ``for_trainer`` passes), leaf
        placement is ``NamedSharding(mesh, P(*spec[:arr.ndim]))`` — the
        same rule ``DataParallelTrainer._put_batch`` applies, so the
        trainer's placement check finds the batch already resident and the
        guarded dispatch is transfer-free;
      - with ``sharding``, that sharding is used for every leaf;
      - with neither, a plain ``jax.device_put`` to the default device.

    The producer starts lazily on first ``next()`` (construction has no
    side effects on the wrapped iterator), preserves source order exactly,
    propagates exceptions, and is joined by ``reset()``/``close()``/GC.
    Only single-process meshes are supported — multi-host feeds go through
    ``make_array_from_process_local_data`` in the trainer instead.
    """

    def __init__(self, source, sharding=None, mesh=None, data_spec=None,
                 depth: Optional[int] = None, name: str = "feed",
                 restarts: Optional[int] = None):
        self._source = source
        self._sharding = sharding
        self._mesh = mesh
        self._data_spec = data_spec
        if sharding is not None and mesh is not None:
            raise MXNetError("pass sharding OR mesh+data_spec, not both")
        self._depth = max(feed_depth() if depth is None else int(depth), 1)
        self._max_restarts = int(env.get("MXNET_TPU_FEED_RESTARTS")
                                 if restarts is None else restarts)
        self.name = name
        self.batch_size = getattr(source, "batch_size", 0)
        self.restarts = 0            # producer restarts taken (supervised)
        self.producer_leaks = 0      # producer threads abandoned at join
        self._q: Optional[queue.Queue] = None
        self._stop: Optional[threading.Event] = None
        self._producer: Optional[threading.Thread] = None
        self._eof = False
        self._peek = None
        self.stall_seconds = 0.0
        self.batches_delivered = 0
        # resumable-input cursor (elastic fault tolerance): epoch counts
        # reset() calls on the wrapped source, _epoch_delivered counts
        # batches handed out THIS epoch, _skip is a pending fast-forward
        # the producer consumes (host-only, no device placement) when it
        # starts after load_state_dict
        self._epoch = 0
        self._epoch_delivered = 0
        self._skip = 0

    @classmethod
    def for_trainer(cls, source, trainer, depth: Optional[int] = None,
                    name: str = "feed"):
        """A feed whose leaves land with the trainer's input sharding
        (``trainer.mesh`` + ``trainer.data_spec`` — replicated, dp-sharded,
        or context-parallel, whatever the trainer was configured with)."""
        if getattr(trainer, "_is_multiprocess", lambda: False)():
            raise MXNetError(
                "DeviceFeed targets single-process meshes; multi-host "
                "batch feeding stays on the trainer's "
                "make_array_from_process_local_data path")
        return cls(source, mesh=trainer.mesh,
                   data_spec=getattr(trainer, "data_spec", None),
                   depth=depth, name=name)

    # -- placement -----------------------------------------------------------
    @staticmethod
    def _already_placed(raw, sharding) -> bool:
        """Skip the no-op device_put when the array already satisfies the
        target placement — same rule as DataParallelTrainer._put_batch
        (through a tunneled backend even a no-op put round-trips the
        buffer)."""
        import jax
        if not isinstance(raw, jax.Array):
            return False
        cur = getattr(raw, "sharding", None)
        if cur is None:
            return False
        dev = set(cur.device_set)
        want = set(sharding.device_set)
        return dev == want and (
            len(want) == 1 or cur.is_equivalent_to(sharding, raw.ndim))

    def _put_raw(self, raw):
        import jax
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            spec = self._data_spec if self._data_spec is not None \
                else PartitionSpec()
            ndim = getattr(raw, "ndim", None)
            if ndim is None:
                ndim = _np.asarray(raw).ndim
            clipped = PartitionSpec(*tuple(spec)[:ndim])
            target = NamedSharding(self._mesh, clipped)
            if self._already_placed(raw, target):
                return raw
            return jax.device_put(raw, target)
        if self._sharding is not None:
            if self._already_placed(raw, self._sharding):
                return raw
            return jax.device_put(raw, self._sharding)
        return jax.device_put(raw)

    def _place_leaf(self, v):
        from ..ndarray import NDArray
        if isinstance(v, NDArray):
            if type(v) is not NDArray:
                # sparse (CSR/row-sparse) and other subclasses carry their
                # own payload layout — pass through unplaced
                return v
            return NDArray(self._put_raw(v._data), v.ctx)
        if v is None or isinstance(v, (int, float, str, bytes)):
            return v
        return self._put_raw(v)

    def _place(self, item):
        from ..io.io import DataBatch
        if isinstance(item, DataBatch):
            out = DataBatch(
                [self._place_leaf(d) for d in (item.data or [])] or None,
                [self._place_leaf(l) for l in (item.label or [])] or None,
                pad=item.pad, index=item.index, bucket_key=item.bucket_key,
                provide_data=item.provide_data,
                provide_label=item.provide_label)
            return out
        if isinstance(item, (list, tuple)):
            return type(item)(self._place_leaf(v) for v in item)
        return self._place_leaf(item)

    # -- producer ------------------------------------------------------------
    _TRANSIENT = (OSError, ConnectionError, TimeoutError)

    def _produce(self, stop: threading.Event, q: "queue.Queue"):
        """Producer body, optionally supervised: with restarts budgeted
        (``restarts=``/``MXNET_TPU_FEED_RESTARTS``) a TRANSIENT source
        error re-opens the iterator and fast-forwards host-side past
        everything already queued — batches are delivered exactly once,
        in order, and ``mx_feed_producer_restarts_total`` is booked. A
        non-transient error (or an exhausted budget) still surfaces at
        the consumer's next()."""
        from .. import faults as _faults
        restarts_left = self._max_restarts
        produced = 0
        skip, self._skip = self._skip, 0
        # all of this producer's spans group under one root context so a
        # trace viewer shows the feed as a single causal track
        root = _tracing.new_root(self.name) if _tracing._ENABLED else None
        while True:
            try:
                it = iter(self._source)
                # resume/restart fast-forward: replay the source up to the
                # restored cursor plus already-produced batches on this
                # thread, host-side only — skipped batches are never
                # placed on device, so rewind costs no transfers
                for _ in range(skip + produced):
                    next(it)
                while not stop.is_set():
                    if _faults._ACTIVE:
                        _faults.check("feed.produce")
                    if _tracing._ENABLED:
                        t0 = time.perf_counter()
                        item = next(it)
                        t1 = time.perf_counter()
                        placed = self._place(item)
                        t2 = time.perf_counter()
                        _tracing.record_span("mx.feed.produce", t0, t1,
                                             parent=root, source=self.name,
                                             batch=produced)
                        _tracing.record_span("mx.feed.put", t1, t2,
                                             parent=root, source=self.name,
                                             batch=produced)
                    else:
                        item = next(it)
                        placed = self._place(item)
                    if not _bounded_put(q, placed, stop):
                        return
                    produced += 1
                return
            except StopIteration:
                _bounded_put(q, _END, stop)
                return
            except Exception as e:
                if restarts_left > 0 and not stop.is_set() and \
                        isinstance(e, self._TRANSIENT
                                   + (_faults.FaultInjected,)):
                    restarts_left -= 1
                    self.restarts += 1
                    from .. import telemetry as _telem
                    if _telem._ENABLED:
                        _telem.record_feed_producer_restart(self.name)
                    continue
                _bounded_put(q, e, stop)  # surfaced at the consumer's next()
                return

    def _ensure_producer(self):
        if self._producer is not None and self._producer.is_alive():
            return
        if self._q is None or self._producer is None:
            import sys
            iv = float(env.get("MXNET_TPU_FEED_GIL_INTERVAL"))
            if iv > 0 and sys.getswitchinterval() > iv:
                # producer and consumer interleave on the GIL; the default
                # 5 ms switch interval stalls the dispatch loop behind a
                # producer mid-batch (measured ~2 ms/step on a 1-core
                # host). Lowered once, process-wide, documented in
                # docs/input_pipeline.md; MXNET_TPU_FEED_GIL_INTERVAL=0
                # opts out.
                sys.setswitchinterval(iv)
            self._stop = threading.Event()
            self._q = queue.Queue(maxsize=self._depth)
            self._producer = threading.Thread(
                target=self._produce, args=(self._stop, self._q),
                daemon=True, name=f"mx-device-feed-{self.name}")
            self._producer.start()

    def _stop_producer(self):
        if self._producer is not None and self._stop is not None:
            self._stop.set()
            timeout = max(float(env.get("MXNET_TPU_FEED_JOIN_TIMEOUT")),
                          0.01)
            # unblock a producer stuck in put(), then join; drain again in
            # case it completed one more put before seeing the stop flag
            for _ in range(2):
                try:
                    while True:
                        self._q.get_nowait()
                except queue.Empty:
                    pass
                self._producer.join(timeout=timeout)
                if not self._producer.is_alive():
                    break
            if self._producer.is_alive():
                # blocked inside the wrapped source (not our put(), which
                # polls the stop flag) — abandoning it leaks the thread
                # until the source unblocks; say so LOUDLY and count it
                import warnings
                self.producer_leaks += 1
                warnings.warn(
                    f"DeviceFeed {self.name!r}: producer thread did not "
                    f"exit within {2 * timeout:.1f}s and was abandoned "
                    "(blocked inside the wrapped source?); the thread "
                    "leaks until the source unblocks — booked in "
                    "mx_feed_producer_leaks_total "
                    "(MXNET_TPU_FEED_JOIN_TIMEOUT tunes the wait)",
                    RuntimeWarning, stacklevel=3)
                from .. import telemetry as _telem
                if _telem._ENABLED:
                    _telem.record_feed_producer_leak(self.name)
        self._producer = None
        self._q = None
        self._stop = None

    # -- consumer protocol ---------------------------------------------------
    def next(self):
        if self._eof:
            raise StopIteration
        self._ensure_producer()
        t0 = None
        try:
            item = self._q.get_nowait()
        except queue.Empty:
            t0 = time.perf_counter()
            while True:
                try:
                    item = self._q.get(timeout=1.0)
                    break
                except queue.Empty:
                    if self._producer is None or \
                            not self._producer.is_alive():
                        raise MXNetError(
                            "DeviceFeed producer thread died without "
                            "delivering a batch or an error")
            self.stall_seconds += time.perf_counter() - t0
        from .. import telemetry as _telem
        if _telem._ENABLED:
            if t0 is not None:
                _telem.record_feed_stall(self.stall_seconds, source=self.name)
            _telem.record_feed_depth(self._q.qsize(), source=self.name)
        if item is _END:
            self._eof = True
            # producer exited on its own; forget it so reset() restarts
            self._producer = None
            self._q = None
            self._stop = None
            raise StopIteration
        if isinstance(item, Exception):
            self._stop_producer()
            raise item
        self.batches_delivered += 1
        self._epoch_delivered += 1
        return item

    def __next__(self):
        return self.next()

    def __iter__(self):
        return self

    def iter_next(self):
        if self._peek is not None:
            return True
        try:
            self._peek = self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self._peek.data if self._peek is not None else None

    def getlabel(self):
        return self._peek.label if self._peek is not None else None

    def getpad(self):
        return getattr(self._peek, "pad", 0) if self._peek is not None else 0

    def reset(self):
        """Stop + join the producer, reset the wrapped source, start a fresh
        epoch. Exactly one inner ``reset()`` per call, so seeded shuffles
        advance the same way they would without the wrapper."""
        self._stop_producer()
        self._peek = None
        self._eof = False
        self._epoch += 1
        self._epoch_delivered = 0
        self._skip = 0
        if hasattr(self._source, "reset"):
            self._source.reset()

    # -- resumable input (elastic fault tolerance) ---------------------------
    def state_dict(self):
        """Durable cursor: which epoch the wrapped source is on and how
        many batches this epoch were consumed (a peeked-but-unused batch
        doesn't count). With a seeded source, ``load_state_dict`` on a
        fresh process replays the exact remaining batch sequence."""
        d = {"epoch": self._epoch,
             "cursor": self._epoch_delivered
             - (1 if self._peek is not None else 0),
             "delivered": self.batches_delivered}
        if hasattr(self._source, "state_dict"):
            d["source"] = self._source.state_dict()
        return d

    def load_state_dict(self, d):
        """Rewind to a saved cursor. A source snapshot (epoch-level state:
        shuffle order, shard assignment — anything ``reset()`` advances)
        is authoritative over the reset-replay; either way the producer
        still fast-forwards ``cursor`` batches host-side when it starts —
        the intra-epoch position is the FEED's knowledge, because the
        producer prefetches ahead of what the consumer was ever handed."""
        self._stop_producer()
        self._peek = None
        self._eof = False
        self._epoch = int(d.get("epoch", 0))
        src = d.get("source")
        if src is not None and hasattr(self._source, "load_state_dict"):
            self._source.load_state_dict(src)
        else:
            for _ in range(self._epoch):
                if hasattr(self._source, "reset"):
                    self._source.reset()
        self._skip = int(d.get("cursor", 0))
        self._epoch_delivered = int(d.get("cursor", 0))
        self.batches_delivered = int(d.get("delivered",
                                           self._epoch_delivered))

    def close(self):
        self._stop_producer()

    def __del__(self):
        try:
            self._stop_producer()
        except Exception:
            pass

    def __len__(self):
        return len(self._source)

    # -- DataIter surface passthrough ---------------------------------------
    @property
    def provide_data(self):
        return getattr(self._source, "provide_data", None)

    @property
    def provide_label(self):
        return getattr(self._source, "provide_label", None)


def maybe_wrap(source, sharding=None, mesh=None, data_spec=None,
               name: str = "feed"):
    """Wrap ``source`` in a DeviceFeed when the async feed is enabled
    (``MXNET_TPU_FEED_DEPTH`` > 0), the source is not already wrapped, and
    the process is single-controller. Used by the fit loops; returns the
    source unchanged otherwise."""
    if isinstance(source, DeviceFeed) or feed_depth() <= 0:
        return source
    try:
        import jax
        if jax.process_count() > 1:
            return source
    except Exception:
        return source
    return DeviceFeed(source, sharding=sharding, mesh=mesh,
                      data_spec=data_spec, name=name)
