"""Compilation engine: process-wide executable cache + buffer-donation policy.

The reference framework funnels every training step through ONE compiled
CachedOp/GraphExecutor artifact with planned memory reuse
(src/imperative/cached_op.h, src/executor/graph_executor.cc). This module is
the jax_graft analog of that shared engine state:

  - a process-wide compilation cache keyed on (graph-structure fingerprint,
    input signature, train flag) so N instances of the same model share one
    set of XLA executables instead of compiling privately per instance
    (gluon HybridBlock and symbol Executor both publish into it);
  - wiring for jax's persistent on-disk compilation cache via the
    ``MXNET_TPU_COMPILATION_CACHE_DIR`` environment variable, so repeat
    processes skip recompiles entirely;
  - the buffer-donation policy used by the optimizer update kernels
    (weight/optimizer-state aliasing a la arXiv:2004.13336's weight-update
    sharding — donated inputs alias their outputs in-place on TPU);
  - hit/miss/trace/compile-time/donation counters surfaced through
    ``profiler.compilation_stats()`` so cache regressions are visible.
"""
from __future__ import annotations

import hashlib
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional, Tuple

from . import hlo_audit

__all__ = ["lookup", "insert", "clear_compilation_cache", "cache_stats",
           "hlo_audit",
           "reset_stats", "donation_enabled", "record_donation",
           "compile_timer", "record_trace", "record_execution",
           "estimate_cost", "structural_fingerprint", "graph_fingerprint",
           "config_fingerprint", "region_digest", "pin", "unpin",
           "pinned_count",
           "async_feed", "DeviceFeed", "DispatchWindow", "PendingScalar"]


def __getattr__(name):
    # the async feed pulls in jax/ndarray machinery; keep it off the
    # import path of the light engine counters (PEP 562, same idiom as
    # the package root)
    if name == "async_feed":
        import importlib
        mod = importlib.import_module(".async_feed", __name__)
        globals()[name] = mod
        return mod
    if name in ("DeviceFeed", "DispatchWindow", "PendingScalar"):
        from . import async_feed as _af
        val = getattr(_af, name)
        globals()[name] = val
        return val
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


_LOCK = threading.RLock()
_CACHE: Dict[Tuple, Any] = {}
# serving/predict artifacts pin their cache entries (refcounted) so a
# fingerprint-scoped invalidation — e.g. one model's clear_cache — cannot
# evict an executable another live Predictor/serving bucket depends on
_PINS: Dict[Tuple, int] = {}

_STATS = {
    "hits": 0,            # shared-cache lookups that returned an artifact
    "misses": 0,          # lookups that required a fresh build
    "traces": 0,          # python-level retraces of cached forwards
    "compiles": 0,        # artifact builds (one per miss that completed)
    "compile_seconds": 0.0,
    "fwd_executions": 0,  # compiled forward invocations (gluon cached path)
    "bwd_executions": 0,  # compiled pullback invocations (no fwd recompute)
    "donated_updates": 0, # optimizer update calls that donated buffers
    "step_executions": 0, # fused trainer-step artifact invocations
    "flops_executed": 0.0,  # cost_analysis FLOPs of executed artifacts
                            # (telemetry's MFU numerator; 0 when telemetry
                            # is off — costs are only captured then)
    "bytes_executed": 0.0,  # cost_analysis bytes-accessed of executed
                            # artifacts (the roofline ledger's bytes axis)
    "cost_capture_failures": 0,  # estimate_cost lowerings that failed
                                 # (mirrored to mx_cost_capture_failures_total)
}


# ---------------------------------------------------------------------------
# Persistent on-disk XLA cache (MXNET_TPU_COMPILATION_CACHE_DIR)
# ---------------------------------------------------------------------------

_persistent_dir = None


def _init_persistent_cache():
    """Point jax's persistent compilation cache at the user-chosen directory.
    Safe to call before any backend initializes (pure config updates)."""
    global _persistent_dir
    d = os.environ.get("MXNET_TPU_COMPILATION_CACHE_DIR")
    if not d or _persistent_dir == d:
        return
    try:
        import jax
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        _persistent_dir = d
    except Exception:
        pass


_init_persistent_cache()


def persistent_cache_dir() -> Optional[str]:
    return _persistent_dir


# ---------------------------------------------------------------------------
# Shared executable cache
# ---------------------------------------------------------------------------

def lookup(key: Tuple):
    """Fetch a shared artifact; counts a hit or a miss."""
    with _LOCK:
        ent = _CACHE.get(key)
        if ent is None:
            _STATS["misses"] += 1
        else:
            _STATS["hits"] += 1
        return ent


def insert(key: Tuple, artifact):
    with _LOCK:
        _CACHE[key] = artifact
    return artifact


def clear_compilation_cache(fingerprint=None, force=False):
    """Drop shared executables — all of them, or only the entries whose key
    carries `fingerprint` (HybridBlock.clear_cache uses the latter so one
    block's invalidation doesn't flush unrelated models). Entries pinned by
    live Predictor/serving artifacts survive unless ``force=True`` (tests
    that must reset the world completely)."""
    with _LOCK:
        if fingerprint is None:
            victims = list(_CACHE)
        else:
            victims = [k for k in _CACHE if fingerprint in k]
        for k in victims:
            if not force and _PINS.get(k):
                continue
            del _CACHE[k]
        if force:
            if fingerprint is None:
                _PINS.clear()
            else:
                for k in [k for k in _PINS if fingerprint in k]:
                    del _PINS[k]


def pin(key: Tuple) -> None:
    """Refcount-pin a cache entry against non-forced invalidation. A serving
    artifact holds one pin per bucket; ``Predictor.reshape`` releases the
    old shape's pin when it rebinds (never leaks it)."""
    with _LOCK:
        if key in _CACHE:
            _PINS[key] = _PINS.get(key, 0) + 1


def unpin(key: Tuple) -> None:
    """Release one pin; the entry becomes evictable at refcount zero."""
    with _LOCK:
        n = _PINS.get(key, 0)
        if n <= 1:
            _PINS.pop(key, None)
        else:
            _PINS[key] = n - 1


def pinned_count() -> int:
    """Number of distinct pinned cache entries (serving-resident artifacts)."""
    with _LOCK:
        return len(_PINS)


def cache_size() -> int:
    with _LOCK:
        return len(_CACHE)


def cache_stats() -> Dict[str, Any]:
    with _LOCK:
        st = dict(_STATS)
        st["artifacts"] = len(_CACHE)
        st["pinned"] = len(_PINS)
        st["persistent_cache_dir"] = _persistent_dir
        return st


def reset_stats():
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0.0 if isinstance(_STATS[k], float) else 0


def _bump(key, n=1):
    with _LOCK:
        _STATS[key] += n


def record_trace():
    _bump("traces")


def record_execution(kind: str, flops: float = 0.0,
                     bytes_accessed: float = 0.0, region: str = None,
                     steps: int = 1, estimated: bool = False,
                     cost: Dict[str, float] = None):
    """Account ``steps`` executions of a compiled artifact.

    This is the ONE funnel both FLOPs accounts flow through: the aggregate
    ``flops_executed``/``bytes_executed`` counters (telemetry's MFU
    numerator) and — when ``region`` is given and telemetry is enabled —
    the per-region roofline ledger (telemetry/roofline.py), so the
    ledger's per-region sum always reconciles with the aggregate.
    ``estimated`` flags heuristic costs (the gluon bwd=2x-fwd fallback) so
    ledger rows built on them render distinguishably. Host arithmetic
    only; hot-path safe."""
    with _LOCK:
        if kind == "fwd":
            _STATS["fwd_executions"] += steps
        elif kind == "step":
            _STATS["step_executions"] += steps
        else:
            _STATS["bwd_executions"] += steps
        if flops:
            _STATS["flops_executed"] += flops
        if bytes_accessed:
            _STATS["bytes_executed"] += bytes_accessed
    if region is not None:
        from .. import telemetry as _telem
        if _telem._ENABLED:
            _telem.roofline.record(region, flops=flops,
                                   bytes_accessed=bytes_accessed,
                                   steps=steps, kind=kind,
                                   estimated=estimated, cost=cost)


# cost_analysis keys -> estimate_cost fields (operand-level "bytes
# accessedN{}" keys are folded into bytes_in/bytes_out below)
_COST_KEYS = (("flops", "flops"), ("bytes accessed", "bytes_accessed"),
              ("transcendentals", "transcendentals"))


def estimate_cost(jitted, *args, kind: str = "artifact",
                  region: Optional[str] = None,
                  overlap_expected: bool = False) -> Dict[str, float]:
    """XLA cost-model + memory estimate for a jitted callable at example
    args: ``{"flops", "bytes_accessed", "bytes_in", "bytes_out",
    "transcendentals", "peak_memory_bytes", "temp_memory_bytes"}`` (keys
    present when the backend reports them; empty dict when it has no cost
    model). Captured ONCE per artifact at build time while telemetry is
    enabled — the AOT lower+compile shares XLA's compilation caches, and
    the result feeds the MFU gauge and the per-region roofline ledger.

    Lowering failures are COUNTED, not swallowed: the engine's
    ``cost_capture_failures`` stat and the ``mx_cost_capture_failures_total``
    counter (labeled by artifact kind) both tick, so a backend that stops
    reporting costs shows up on the dashboard instead of silently zeroing
    every ledger row."""
    try:
        compiled = jitted.lower(*args).compile()
        try:
            # post-lowering hazard audit (mxcheck, docs/static_analysis.md):
            # same AOT compile, one extra text scan per artifact. Donation
            # expectation is best-effort introspection of the jit wrapper;
            # the alias-pair count lands in the fingerprint either way and
            # tools/hlo_audit_gate.py diffs it.
            donate = bool(getattr(getattr(jitted, "_jit_info", None),
                                  "donate_argnums", ()) or ())
            hlo_audit.audit_compiled(
                compiled, kind=kind, region=region or kind,
                overlap_expected=overlap_expected,
                donation_expected=donate)
        except Exception:
            pass  # the audit must never fail a cost capture
        c = compiled.cost_analysis()
        if isinstance(c, (list, tuple)):
            c = c[0] if c else {}
        out = {}
        for src, dst in _COST_KEYS:
            v = c.get(src)
            if v is not None and float(v) >= 0:
                out[dst] = float(v)
        bytes_in = bytes_out = 0.0
        for k, v in c.items():
            if k.startswith("bytes accessed") and k != "bytes accessed":
                if "out" in k:
                    bytes_out += float(v)
                else:
                    bytes_in += float(v)
        if bytes_in:
            out["bytes_in"] = bytes_in
        if bytes_out:
            out["bytes_out"] = bytes_out
        try:
            m = compiled.memory_analysis()
            if m is not None:
                temp = float(getattr(m, "temp_size_in_bytes", 0) or 0)
                out["temp_memory_bytes"] = temp
                out["peak_memory_bytes"] = temp + float(
                    getattr(m, "argument_size_in_bytes", 0) or 0) + float(
                    getattr(m, "output_size_in_bytes", 0) or 0)
        except Exception:
            pass  # memory analysis is best-effort extra detail
        return out
    except Exception:
        _bump("cost_capture_failures")
        from .. import telemetry as _telem
        if _telem._ENABLED:
            _telem.counter(
                "mx_cost_capture_failures_total",
                "estimate_cost lowerings that raised (regions fall back "
                "to zero/heuristic costs — see engine.cache_stats)",
                ("kind",)).labels(kind).inc()
        return {}


@contextmanager
def compile_timer(name: str = "build"):
    """Times an artifact build; feeds both the stats dict and the profiler's
    aggregate table (category 'compilation')."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        with _LOCK:
            _STATS["compiles"] += 1
            _STATS["compile_seconds"] += t1 - t0
        try:
            from .. import profiler as _profiler
            _profiler._record(name, "compilation", t0, t1)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Buffer donation policy
# ---------------------------------------------------------------------------

_donation_cache = {"value": None}


def donation_enabled() -> bool:
    """True when donate_argnums should be used for optimizer updates.
    MXNET_TPU_DONATION=0/1 overrides; otherwise enabled on accelerator
    backends (CPU ignores donation and would warn on every call)."""
    ov = os.environ.get("MXNET_TPU_DONATION")
    if ov is not None:
        return ov.lower() not in ("0", "false", "off")
    with _LOCK:
        if _donation_cache["value"] is None:
            try:
                import jax
                _donation_cache["value"] = \
                    jax.default_backend() not in ("cpu",)
            except Exception:
                _donation_cache["value"] = False
        return _donation_cache["value"]


def record_donation(n: int = 1):
    _bump("donated_updates", n)


# ---------------------------------------------------------------------------
# Graph-structure fingerprints
# ---------------------------------------------------------------------------

# bookkeeping attrs that vary per instance without changing the computation
_SKIP_ATTRS = {
    "_prefix", "_params", "_children", "_reg_params", "_scope",
    "_forward_hooks", "_forward_pre_hooks", "_empty_init_guard",
    "_active", "_flags", "_fingerprint_memo",
}

_SCALARS = (int, float, bool, str, bytes, type(None))


def _stable_value(v):
    """A deterministic token for a config attribute. Scalars and containers
    of scalars hash by value; anything opaque (callables, arrays, objects)
    hashes by identity so two blocks never falsely share executables."""
    if isinstance(v, _SCALARS):
        return repr(v)
    if isinstance(v, (tuple, list)):
        return "(" + ",".join(_stable_value(x) for x in v) + ")"
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{k!r}:{_stable_value(v[k])}" for k in sorted(v, key=repr)) + "}"
    return f"id:{id(v)}"


def _block_config_items(block):
    items = []
    for k in sorted(vars(block)):
        if k in _SKIP_ATTRS or k.startswith("_cached"):
            continue
        v = vars(block)[k]
        if hasattr(v, "_deferred_init") or hasattr(v, "_reg_params"):
            continue  # params/children are fingerprinted structurally below
        items.append((k, _stable_value(v)))
    return items


def structural_fingerprint(block) -> str:
    """Deterministic digest of a Block tree: class, scalar config, parameter
    shapes/dtypes, children (recursively). Two instances of the same model
    definition produce the same fingerprint and therefore share compiled
    executables; prefixes/names are deliberately excluded."""
    h = hashlib.sha1()

    def walk(b):
        h.update(f"<{type(b).__module__}.{type(b).__qualname__}".encode())
        for k, v in _block_config_items(b):
            h.update(f"|{k}={v}".encode())
        for k, p in getattr(b, "_reg_params", {}).items():
            h.update(f"|p:{k}:{tuple(p.shape or ())}:{p.dtype}".encode())
        for k, c in getattr(b, "_children", {}).items():
            h.update(f"|c:{k}".encode())
            walk(c)
        h.update(b">")

    walk(block)
    return h.hexdigest()


def graph_fingerprint(text: str) -> str:
    """Digest of an explicit graph serialization (Symbol.tojson)."""
    return hashlib.sha1(text.encode()).hexdigest()


def config_fingerprint(**config) -> Tuple:
    """Deterministic token tuple for a trainer/executor configuration.
    Values go through ``_stable_value`` (scalars and containers by value,
    opaque objects by identity). The fused-step caches key on this so two
    configurations that must compile apart — e.g. distinct
    zero-update/bucket-size/comm-dtype settings — never share an artifact,
    while N instances of one configuration share a single executable."""
    return tuple((k, _stable_value(config[k])) for k in sorted(config))


def region_digest(*parts) -> str:
    """Stable short digest of a compile-key tuple, used for roofline-ledger
    region names (parallel/step_program.py): two configurations that compile
    apart ledger apart, N same-config trainers share one row."""
    return hashlib.sha1(repr(parts).encode()).hexdigest()[:6]
