"""XLA flag helper for comm/compute overlap (async collectives + the
latency-hiding scheduler).

The overlapped step (parallel/overlap.py) arranges the HLO so each fusion
bucket's collective is issuable while later backward segments still
compute; whether the DMA actually hides under the dots is the compiler
scheduler's call. On TPU/GPU that scheduler sits behind XLA flags which
are read ONCE, when the backend initializes — setting them after the first
jax call is a silent no-op. ``ensure_overlap_flags()`` appends the missing
flags to ``XLA_FLAGS`` when called early enough and warns (once per
process) when it is already too late; `DataParallelTrainer(
overlap_grads=True)` calls it at construction.

Env knobs:
  - ``XLA_FLAGS``: flags already present (by flag name) are never
    overridden — operator settings win;
  - ``MXNET_TPU_OVERLAP_XLA_FLAGS``: 'off' disables the helper entirely;
    otherwise a space-separated flag list REPLACING the built-in set
    (and bypassing the platform filter — you own the spelling);
  - ``JAX_PLATFORMS``/``JAX_PLATFORM_NAME``: consulted to decide whether
    the --xla_tpu_* spellings are safe — XLA aborts on unknown flags, and
    only libtpu-linked builds parse them.
"""
from __future__ import annotations

import os
import warnings
from typing import Tuple

from ..base import env

__all__ = ["OVERLAP_XLA_FLAGS", "OVERLAP_XLA_FLAGS_TPU",
           "OVERLAP_XLA_FLAGS_GPU", "tpu_expected", "overlap_flags",
           "backend_initialized", "ensure_overlap_flags"]

# Async collectives give each DMA its own start/done pair instead of one
# blocking instruction; the latency-hiding scheduler then moves unrelated
# compute between start and done. The TPU spellings cover all-reduce /
# reduce-scatter fusion plus the gather-back; the GPU spelling enables the
# LHS wholesale. XLA ABORTS the process on unknown flags in XLA_FLAGS, and
# the TPU spellings only exist in libtpu-linked builds — overlap_flags()
# therefore drops the TPU group unless a TPU backend is in play.
OVERLAP_XLA_FLAGS_TPU: Tuple[str, ...] = (
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
)
OVERLAP_XLA_FLAGS_GPU: Tuple[str, ...] = (
    "--xla_gpu_enable_latency_hiding_scheduler=true",
)
OVERLAP_XLA_FLAGS: Tuple[str, ...] = (OVERLAP_XLA_FLAGS_TPU
                                      + OVERLAP_XLA_FLAGS_GPU)

env.declare("MXNET_TPU_OVERLAP_XLA_FLAGS", "", str,
            "Override for ensure_overlap_flags: 'off' disables the helper, "
            "any other non-empty value is a space-separated XLA flag list "
            "used instead of the built-in async-collective set")

_WARNED = [False]


def tpu_expected() -> bool:
    """Whether this process will (or could) bring up a TPU backend — the
    only builds whose flag parser knows the --xla_tpu_* spellings."""
    plats = (os.environ.get("JAX_PLATFORMS")
             or os.environ.get("JAX_PLATFORM_NAME") or "").lower()
    if "tpu" in plats:
        return True
    if plats:  # an explicit non-TPU platform list pins the backend
        return False
    try:
        import libtpu  # noqa: F401
        return True
    except ImportError:
        return False


def overlap_flags() -> Tuple[str, ...]:
    """The flag set ensure_overlap_flags applies, after the env override
    and the platform filter (TPU spellings abort non-TPU flag parsers)."""
    override = str(env.get("MXNET_TPU_OVERLAP_XLA_FLAGS")).strip()
    if override.lower() == "off":
        return ()
    if override:
        return tuple(override.split())
    if tpu_expected():
        return OVERLAP_XLA_FLAGS
    return OVERLAP_XLA_FLAGS_GPU


def backend_initialized() -> bool:
    """Whether jax already initialized a backend (XLA_FLAGS frozen)."""
    try:
        from jax._src import xla_bridge as _xb
    except ImportError:  # pragma: no cover - jax always present here
        return False
    return bool(getattr(_xb, "_backends", None))


def ensure_overlap_flags(warn: bool = True) -> bool:
    """Append the missing overlap flags to ``XLA_FLAGS`` if the backend has
    not initialized yet. Returns True when every flag is (now) in effect;
    False when the helper was disabled or came too late — in the late case
    a UserWarning fires once per process (suppress with warn=False)."""
    flags = overlap_flags()
    if not flags:
        return False
    have = os.environ.get("XLA_FLAGS", "")
    present = {f.split("=", 1)[0] for f in have.split()}
    missing = [f for f in flags if f.split("=", 1)[0] not in present]
    if not missing:
        return True
    if backend_initialized():
        if warn and not _WARNED[0]:
            _WARNED[0] = True
            warnings.warn(
                "ensure_overlap_flags: the XLA backend is already "
                "initialized, so the async-collective / latency-hiding "
                "scheduler flags cannot take effect this process. Set "
                "XLA_FLAGS before launch or call ensure_overlap_flags() "
                "before the first jax operation (docs/data_parallel.md, "
                "'Overlapping gradient communication').",
                UserWarning, stacklevel=2)
        return False
    os.environ["XLA_FLAGS"] = (have + " " + " ".join(missing)).strip()
    return True
