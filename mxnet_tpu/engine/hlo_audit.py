"""Compiled-HLO hazard audit: what did XLA *actually* build? (mxcheck)

The AST passes (tools/mxlint/passes/collective_order.py, partition_spec.py)
prove properties of the python we wrote; this module audits the optimized
HLO the compiler produced — hazards no source-level analysis can see:

  host_transfer   infeed/outfeed/host callbacks in a step artifact: every
                  execution stalls the TPU on the host roundtrip (the
                  host-sync lint rule's compiled-program twin)
  f64             f64 ops in a framework whose numerics are f32/bf16 —
                  almost always an accidental promotion (python float,
                  np.float64 constant) silently doubling bytes + flops
  sync_collective collectives that failed to become async ``-start/-done``
                  pairs when grad overlap is ON: the schedule serialized
                  compute behind communication (arXiv:2301.13062 framing)
  no_alias        donation that produced zero input/output aliases — the
                  donated buffers were copied, not reused

Hooked into ``engine.estimate_cost`` (the once-per-artifact AOT
lower+compile already captured for the roofline ledger), so every fused DP
step, 1F1B pipeline tick, and serving artifact gets a **hazard
fingerprint**: counts per hazard + the collective mix, persisted as JSON
next to the persistent compilation cache (``MXNET_TPU_HLO_AUDIT_DIR``,
default ``$MXNET_TPU_COMPILATION_CACHE_DIR/hlo_audit``) and diffed by the
``tools/hlo_audit_gate.py`` CI gate — a refactor that silently regresses
fusion/overlap/donation fails tier-1 instead of a bench round three PRs
later. Telemetry: ``mx_hlo_hazards_total{kind,region}`` (kind = hazard
vocabulary above) on /statusz and Prometheus.
"""
from __future__ import annotations

import json
import os
import re
import threading
from typing import Any, Dict, List, Optional

__all__ = ["audit_text", "audit_compiled", "fingerprints", "audit_dir",
           "reset", "HAZARD_KINDS"]

HAZARD_KINDS = ("host_transfer", "f64", "sync_collective", "no_alias")

# -- HLO text patterns -------------------------------------------------------
# host boundary crossings: infeed/outfeed ops, is_host_transfer sends/recvs,
# and the cpu-callback custom-calls jax lowers io_callback/pure_callback/
# debug.print to (the planted-regression lane in tests/test_mxcheck.py uses
# exactly that lowering)
_HOST_RE = re.compile(
    r"\b(?:infeed|outfeed)\b"
    r"|is_host_transfer=true"
    r"|custom_call_target=\"(?:xla_python_cpu_callback"
    r"|xla_ffi_python_cpu_callback|xla_python_gpu_callback"
    r"|MoveToHost|MoveFromHost)\"")
_F64_RE = re.compile(r"\bf64\[")
# collective ops: plain form = synchronous (compute waits); ``-start`` =
# async (latency-hiding pair). ``-done`` is the join of a start and is not
# counted separately.
_COLL_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(-start|-done)?\(")
_ALIAS_RE = re.compile(r"\b(?:may|must)-alias\b")
_DONATED_RE = re.compile(r"\bdonated\b")

_LOCK = threading.Lock()
_FINGERPRINTS: Dict[str, Dict[str, Any]] = {}


def audit_dir() -> Optional[str]:
    """Where fingerprints persist: MXNET_TPU_HLO_AUDIT_DIR, else an
    ``hlo_audit/`` subdir of the persistent compilation cache, else None
    (in-memory only)."""
    d = os.environ.get("MXNET_TPU_HLO_AUDIT_DIR")
    if d:
        return d
    cache = os.environ.get("MXNET_TPU_COMPILATION_CACHE_DIR")
    if cache:
        return os.path.join(cache, "hlo_audit")
    return None


def audit_text(hlo_text: str, *, kind: str = "artifact",
               region: str = "", overlap_expected: bool = False,
               donation_expected: bool = False) -> Dict[str, Any]:
    """Scan one optimized-HLO module; return its hazard fingerprint.
    Pure text analysis — no jax import, no device."""
    host = len(_HOST_RE.findall(hlo_text))
    f64 = len(_F64_RE.findall(hlo_text))
    sync = 0
    async_ = 0
    mix: Dict[str, int] = {}
    for m in _COLL_RE.finditer(hlo_text):
        op, suffix = m.group(1), m.group(2)
        if suffix == "-done":
            continue
        key = op + (suffix or "")
        mix[key] = mix.get(key, 0) + 1
        if suffix == "-start":
            async_ += 1
        else:
            sync += 1
    alias = len(_ALIAS_RE.findall(hlo_text))
    donated = len(_DONATED_RE.findall(hlo_text))

    hazards: List[Dict[str, Any]] = []
    if host:
        hazards.append({"kind": "host_transfer", "count": host})
    if f64:
        hazards.append({"kind": "f64", "count": f64})
    if overlap_expected and sync and not async_:
        hazards.append({"kind": "sync_collective", "count": sync})
    if donation_expected and donated and not alias:
        hazards.append({"kind": "no_alias", "count": donated})

    label = region.split("#", 1)[0] if region else kind
    return {
        "version": 1,
        "region": region or kind,
        "label": label,
        "kind": kind,
        "counts": {
            "host_transfers": host,
            "f64_ops": f64,
            "collectives_sync": sync,
            "collectives_async": async_,
            "alias_pairs": alias,
            "donated_params": donated,
        },
        "collectives": mix,
        "hazards": hazards,
    }


def audit_compiled(compiled, *, kind: str = "artifact", region: str = "",
                   overlap_expected: bool = False,
                   donation_expected: bool = False) -> Optional[Dict[str, Any]]:
    """Audit a jax ``Compiled`` object (post-optimization HLO), record the
    fingerprint (memory + telemetry + on-disk). Best-effort: backends that
    cannot render HLO text return None instead of raising into the
    artifact build."""
    try:
        text = compiled.as_text()
    except Exception:
        return None
    if not text:
        return None
    fp = audit_text(text, kind=kind, region=region,
                    overlap_expected=overlap_expected,
                    donation_expected=donation_expected)
    _record(fp)
    return fp


def _record(fp: Dict[str, Any]):
    with _LOCK:
        _FINGERPRINTS[fp["region"]] = fp
    from .. import telemetry as _telem
    if _telem._ENABLED:
        c = _telem.counter(
            "mx_hlo_hazards_total",
            "Hazards the compiled-HLO audit found in built artifacts "
            "(host transfers, f64 ops, unoverlapped collectives, "
            "non-aliasing donation)", ("kind", "region"))
        for h in fp["hazards"]:
            c.labels(h["kind"], fp["label"]).inc(h["count"])
    d = audit_dir()
    if d:
        try:
            os.makedirs(d, exist_ok=True)
            slug = re.sub(r"[^\w.\-]+", "_", fp["region"])[:100]
            path = os.path.join(d, f"{slug}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(fp, f, indent=2, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass  # audit persistence must never fail an artifact build


def fingerprints() -> Dict[str, Dict[str, Any]]:
    """Snapshot of every fingerprint captured in this process (tests and
    /statusz read this; the CI gate reads the on-disk copies)."""
    with _LOCK:
        return {k: dict(v) for k, v in sorted(_FINGERPRINTS.items())}


def reset():
    with _LOCK:
        _FINGERPRINTS.clear()
