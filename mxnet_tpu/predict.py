"""Minimal standalone predict runtime (reference
include/mxnet/c_predict_api.h:1-348 + amalgamation/: the load-and-serve
path that ships without training machinery).

`mxnet_tpu.predict` imports ONLY the symbolic core (symbol graph, ops,
ndarray) — no gluon, no optimizer, no parallel, no io. Together with the
lazy package __init__ this keeps a serving process slim:

    from mxnet_tpu.predict import Predictor
    p = Predictor("model-symbol.json", "model-0000.params",
                  input_shapes={"data": (1, 3, 224, 224)})
    out = p.predict(x)          # numpy in, numpy out

Construction binds the graph and runs the single XLA compile for the
declared input shapes (the c_predict_api contract: shapes fixed at
MXPredCreate, `reshape` rebinds); `predict` afterwards never compiles.

Compiled forwards are **shared, pinned engine artifacts**: the executable
for one (graph fingerprint, full input signature) lives in the process-wide
``mxnet_tpu.engine`` cache under a ``config_fingerprint``-style key, so N
predictors (or N serving buckets — ``mxnet_tpu.serving``) over the same
exported model compile ONCE and every reuse is a visible cache hit in
``compilation_stats()``. Each holder pins its entry (``engine.pin``) so a
fingerprint-scoped invalidation can't evict a live serving executable;
``Predictor.reshape`` releases the old shape's pin when it rebinds, and
``MXNET_TPU_COMPILATION_CACHE_DIR`` persists the XLA executables so a
restarted serving process warms from disk instead of recompiling.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as _np

from .base import MXNetError
from .context import Context, current_context
from .ndarray import NDArray
from . import engine as _engine

__all__ = ["Predictor", "ForwardArtifact", "acquire_forward", "load_params"]


def load_params(param_file: str) -> Tuple[Dict, Dict]:
    """Read a `-0000.params` checkpoint (arg:/aux: key format) without
    importing model/module machinery."""
    from .serialization import load_ndarrays
    arg_params, aux_params = {}, {}
    for k, v in load_ndarrays(param_file).items():
        tp, name = k.split(":", 1) if ":" in k else ("arg", k)
        (arg_params if tp == "arg" else aux_params)[name] = v
    return arg_params, aux_params


# ---------------------------------------------------------------------------
# Shared compiled inference artifacts
# ---------------------------------------------------------------------------

class ForwardArtifact:
    """One compiled inference forward for a (graph, full input signature)
    pair, shared process-wide through the engine cache.

    ``arg_names``/``aux_names`` fix the positional order callers must
    assemble values in; ``__call__`` dispatches the compiled executable and
    returns the raw output arrays WITHOUT a host sync (serving slices and
    syncs at completion time, off the dispatch path).
    """

    __slots__ = ("key", "fn", "arg_names", "aux_names", "num_outputs",
                 "flops", "cost", "region", "_rng_key")

    def __init__(self, key, fn, arg_names, aux_names, num_outputs, rng_key,
                 flops: float = 0.0, cost=None):
        self.key = key
        self.fn = fn
        self.arg_names = arg_names
        self.aux_names = aux_names
        self.num_outputs = num_outputs
        self.flops = flops
        self.cost = cost or {}
        # roofline-ledger row key: the graph fingerprint inside the engine
        # cache key, so every Predictor/serving bucket over one exported
        # model aggregates into one row per compiled signature
        self.region = f"predict#{key[1][:6]}" if len(key) > 1 else "predict"
        self._rng_key = rng_key

    def __call__(self, arg_vals: Sequence, aux_vals: Sequence = ()):
        outs, _ = self.fn(tuple(arg_vals), tuple(aux_vals), self._rng_key)
        from . import telemetry as _telem
        _engine.record_execution(
            "fwd", self.flops,
            bytes_accessed=self.cost.get("bytes_accessed", 0.0),
            region=self.region if _telem._ENABLED else None, cost=self.cost)
        return outs

    def release(self):
        """Drop this holder's pin (the entry stays cached until evicted)."""
        _engine.unpin(self.key)


def _aval_items(avals: Dict[str, Tuple[Tuple[int, ...], str]]):
    return tuple((n,) + (tuple(int(d) for d in s), str(t))
                 for n, (s, t) in sorted(avals.items()))


def acquire_forward(symbol, arg_avals: Dict[str, Tuple[Tuple[int, ...], str]],
                    aux_avals: Optional[Dict[str, Tuple[Tuple[int, ...],
                                                        str]]] = None,
                    sharding_tag: str = "",
                    place: Optional[Callable[[str, Any], Any]] = None
                    ) -> ForwardArtifact:
    """Get-or-build the compiled inference forward for ``symbol`` at the
    given full argument signature, through the process-wide engine cache.

    The key is ``("predict", graph_fingerprint, config_fingerprint(...))``
    over every argument/aux (name, shape, dtype) plus a caller-chosen
    ``sharding_tag`` (serving uses it to compile dp-sharded buckets apart
    from replicated ones). On a miss the artifact is built AND warmed — one
    traced+compiled execution on zeros, placed by ``place(name, zeros)``
    when given (how serving warms each bucket with its real input sharding)
    — so a registry's eager warmup at startup is exactly one call here per
    bucket. The entry comes back pinned; callers own one ``release()``.
    """
    import jax
    import jax.numpy as jnp

    aux_avals = aux_avals or {}
    fp = _engine.graph_fingerprint(symbol.tojson())
    cfg = _engine.config_fingerprint(
        args=_aval_items(arg_avals), aux=_aval_items(aux_avals),
        sharding=sharding_tag)
    key = ("predict", fp, cfg)
    art = _engine.lookup(key)
    if art is None:
        from .symbol.executor import _graph_runner
        with _engine.compile_timer("predict:bind"):
            run, arg_nodes, aux_nodes, _rng = _graph_runner(symbol, False)
            arg_names = tuple(n.name for n in arg_nodes)
            aux_names = tuple(n.name for n in aux_nodes)
            missing = [n for n in arg_names if n not in arg_avals]
            if missing:
                raise MXNetError(
                    f"acquire_forward: no shape/dtype for arguments "
                    f"{missing}")
            jitted = jax.jit(run)
            rng_key = jax.random.PRNGKey(0)

            def zero(name, avals):
                s, t = avals[name]
                z = jnp.zeros(tuple(s), jnp.dtype(t))
                return place(name, z) if place is not None else z

            warm_args = tuple(zero(n, arg_avals) for n in arg_names)
            warm_aux = tuple(zero(n, aux_avals) for n in aux_names)
            cost = {}
            from . import telemetry as _telem
            if _telem._ENABLED:
                # ledger/audit region mirrors the artifact cache key, so
                # two distinct exported graphs fingerprint apart while
                # re-binds of the same graph+signature share one row
                cost = _engine.estimate_cost(
                    jitted, warm_args, warm_aux, rng_key, kind="predict",
                    region=f"predict#{_engine.region_digest(key, 'fwd')}")
            outs, _ = jitted(warm_args, warm_aux, rng_key)
            jax.block_until_ready(outs)  # the single compile, at bind time
            art = ForwardArtifact(key, jitted, arg_names, aux_names,
                                  len(outs), rng_key,
                                  cost.get("flops", 0.0), cost=cost)
            _engine.insert(key, art)
    _engine.pin(key)
    return art


class Predictor:
    """Fixed-shape inference executor over an exported symbol graph
    (reference c_predict_api.h MXPredCreate/MXPredForward/MXPredGetOutput).
    """

    def __init__(self, symbol_file: str, param_file: Optional[str] = None,
                 input_shapes: Optional[Dict[str, Sequence[int]]] = None,
                 ctx: Optional[Context] = None, dtype: str = "float32",
                 dtypes: Optional[Dict[str, str]] = None):
        from . import symbol as sym_mod
        self._sym = sym_mod.load(symbol_file)
        self._ctx = ctx or current_context()
        self._dtype = dtype
        self._dtypes = dict(dtypes or {})
        arg_params, aux_params = ({}, {}) if param_file is None \
            else load_params(param_file)
        self._arg_params = {k: self._to_device(v) for k, v in
                            arg_params.items()}
        self._aux_params = {k: self._to_device(v) for k, v in
                            aux_params.items()}
        known = set(self._arg_params)
        self._input_names = [n for n in self._sym.list_arguments()
                             if n not in known]
        self._art: Optional[ForwardArtifact] = None
        self._shapes: Optional[Dict[str, Tuple[int, ...]]] = None
        if input_shapes:
            self.reshape(input_shapes)

    def _to_device(self, v):
        v = v if isinstance(v, NDArray) else NDArray(v._data)
        return v.as_in_context(self._ctx).handle

    def _input_dtype(self, name: str) -> str:
        return self._dtypes.get(name, self._dtype)

    # -- binding -------------------------------------------------------------
    def reshape(self, input_shapes: Dict[str, Sequence[int]]) -> None:
        """(Re)bind for new input shapes (c_predict_api.h MXPredReshape).
        Acquires the shared pinned artifact for the new signature — the one
        XLA compile, at load time, shared with every other holder of the
        same (graph, signature) — and releases the OLD signature's pin so
        rebinding never leaks a pinned cache entry."""
        missing = [n for n in self._input_names if n not in input_shapes]
        if missing:
            raise MXNetError(
                f"input_shapes missing {missing}; the graph's data inputs "
                f"are {self._input_names}")
        arg_avals = {
            name: (tuple(int(s) for s in shape), self._input_dtype(name))
            for name, shape in input_shapes.items()}
        for name, v in self._arg_params.items():
            arg_avals[name] = (tuple(v.shape), str(v.dtype))
        aux_avals = {name: (tuple(v.shape), str(v.dtype))
                     for name, v in self._aux_params.items()}
        old = self._art
        self._art = acquire_forward(self._sym, arg_avals, aux_avals)
        if old is not None:
            old.release()
        self._shapes = {k: tuple(int(s) for s in v)
                        for k, v in input_shapes.items()}

    # -- serving -------------------------------------------------------------
    def predict(self, *args, **kwargs) -> Union[_np.ndarray,
                                                List[_np.ndarray]]:
        """Positional args follow the graph's input order; kwargs override
        by name. Accepts numpy or NDArray; returns numpy."""
        if len(args) > len(self._input_names):
            raise MXNetError(
                f"predict: {len(args)} positional inputs but the graph has "
                f"only {self._input_names}")
        named = dict(zip(self._input_names, args))
        named.update(kwargs)
        unknown = [n for n in named if n not in self._input_names]
        if unknown:
            raise MXNetError(
                f"predict: unknown inputs {unknown}; the graph's data "
                f"inputs are {self._input_names}")
        missing = [n for n in self._input_names if n not in named]
        if missing:
            raise MXNetError(
                f"predict: missing inputs {missing}; the graph's data "
                f"inputs are {self._input_names}")
        if self._art is None:
            self.reshape({n: tuple(_np.shape(a)) for n, a in named.items()})
        import jax.numpy as jnp
        feed = {}
        for name, a in named.items():
            if self._shapes and tuple(_np.shape(a)) != self._shapes[name]:
                raise MXNetError(
                    f"input {name!r} has shape {tuple(_np.shape(a))}, bound "
                    f"for {self._shapes[name]}; call reshape() for new "
                    "shapes (c_predict_api fixed-shape contract)")
            if isinstance(a, NDArray):
                a = a.handle
            else:
                a = jnp.asarray(_np.asarray(a, self._input_dtype(name)))
            feed[name] = a
        arg_vals = tuple(feed[n] if n in feed else self._arg_params[n]
                         for n in self._art.arg_names)
        aux_vals = tuple(self._aux_params[n] for n in self._art.aux_names)
        outs = self._art(arg_vals, aux_vals)
        res = [_np.asarray(o) for o in outs]
        return res[0] if len(res) == 1 else res

    __call__ = predict

    def close(self) -> None:
        """Release this predictor's pin on its compiled artifact."""
        art, self._art = self._art, None
        if art is not None:
            art.release()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    @property
    def output_names(self) -> List[str]:
        return self._sym.list_outputs()

    @property
    def input_names(self) -> List[str]:
        return list(self._input_names)


def _selftest() -> int:
    """`python -m mxnet_tpu.predict model-prefix N C H W` smoke entry."""
    import sys
    import time
    prefix = sys.argv[1]
    shape = tuple(int(s) for s in sys.argv[2:]) or (1, 3, 224, 224)
    t0 = time.perf_counter()
    p = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                  input_shapes={"data": shape})
    t1 = time.perf_counter()
    out = p.predict(_np.zeros(shape, _np.float32))
    t2 = time.perf_counter()
    print(f"bind+compile {t1 - t0:.2f}s, predict {t2 - t1 :.4f}s, "
          f"out shape {getattr(out, 'shape', [o.shape for o in out])}")
    return 0


if __name__ == "__main__":
    raise SystemExit(_selftest())
