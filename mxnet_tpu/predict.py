"""Minimal standalone predict runtime (reference
include/mxnet/c_predict_api.h:1-348 + amalgamation/: the load-and-serve
path that ships without training machinery).

`mxnet_tpu.predict` imports ONLY the symbolic core (symbol graph, ops,
ndarray) — no gluon, no optimizer, no parallel, no io. Together with the
lazy package __init__ this keeps a serving process slim:

    from mxnet_tpu.predict import Predictor
    p = Predictor("model-symbol.json", "model-0000.params",
                  input_shapes={"data": (1, 3, 224, 224)})
    out = p.predict(x)          # numpy in, numpy out

Construction binds the graph and runs the single XLA compile for the
declared input shapes (the c_predict_api contract: shapes fixed at
MXPredCreate, `reshape` rebinds); `predict` afterwards never compiles.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as _np

from .base import MXNetError
from .context import Context, current_context
from .ndarray import NDArray


def load_params(param_file: str) -> Tuple[Dict, Dict]:
    """Read a `-0000.params` checkpoint (arg:/aux: key format) without
    importing model/module machinery."""
    from .serialization import load_ndarrays
    arg_params, aux_params = {}, {}
    for k, v in load_ndarrays(param_file).items():
        tp, name = k.split(":", 1) if ":" in k else ("arg", k)
        (arg_params if tp == "arg" else aux_params)[name] = v
    return arg_params, aux_params


class Predictor:
    """Fixed-shape inference executor over an exported symbol graph
    (reference c_predict_api.h MXPredCreate/MXPredForward/MXPredGetOutput).
    """

    def __init__(self, symbol_file: str, param_file: Optional[str] = None,
                 input_shapes: Optional[Dict[str, Sequence[int]]] = None,
                 ctx: Optional[Context] = None, dtype: str = "float32"):
        from . import symbol as sym_mod
        self._sym = sym_mod.load(symbol_file)
        self._ctx = ctx or current_context()
        self._dtype = dtype
        arg_params, aux_params = ({}, {}) if param_file is None \
            else load_params(param_file)
        self._params = {**arg_params, **aux_params}
        known = set(self._params)
        self._input_names = [n for n in self._sym.list_arguments()
                             if n not in known]
        self._ex = None
        self._shapes: Optional[Dict[str, Tuple[int, ...]]] = None
        if input_shapes:
            self.reshape(input_shapes)

    # -- binding -------------------------------------------------------------
    def reshape(self, input_shapes: Dict[str, Sequence[int]]) -> None:
        """(Re)bind for new input shapes (c_predict_api.h MXPredReshape).
        Runs the one XLA compile so `predict` is compile-free."""
        missing = [n for n in self._input_names if n not in input_shapes]
        if missing:
            raise MXNetError(
                f"input_shapes missing {missing}; the graph's data inputs "
                f"are {self._input_names}")
        import jax.numpy as jnp
        binds = {}
        for name, shape in input_shapes.items():
            binds[name] = NDArray(
                jnp.zeros(tuple(int(s) for s in shape),
                          jnp.dtype(self._dtype)), self._ctx)
        for name, v in self._params.items():
            v = v if isinstance(v, NDArray) else NDArray(v._data)
            binds[name] = v.as_in_context(self._ctx)
        self._ex = self._sym.bind(self._ctx, binds)
        self._shapes = {k: tuple(int(s) for s in v)
                        for k, v in input_shapes.items()}
        self._ex.forward(is_train=False)  # the single compile, at load time

    # -- serving -------------------------------------------------------------
    def predict(self, *args, **kwargs) -> Union[_np.ndarray,
                                                List[_np.ndarray]]:
        """Positional args follow the graph's input order; kwargs override
        by name. Accepts numpy or NDArray; returns numpy."""
        if len(args) > len(self._input_names):
            raise MXNetError(
                f"predict: {len(args)} positional inputs but the graph has "
                f"only {self._input_names}")
        named = dict(zip(self._input_names, args))
        named.update(kwargs)
        unknown = [n for n in named if n not in self._input_names]
        if unknown:
            raise MXNetError(
                f"predict: unknown inputs {unknown}; the graph's data "
                f"inputs are {self._input_names}")
        missing = [n for n in self._input_names if n not in named]
        if missing:
            raise MXNetError(
                f"predict: missing inputs {missing}; the graph's data "
                f"inputs are {self._input_names}")
        if self._ex is None:
            self.reshape({n: tuple(_np.shape(a)) for n, a in named.items()})
        feed = {}
        for name, a in named.items():
            if self._shapes and tuple(_np.shape(a)) != self._shapes[name]:
                raise MXNetError(
                    f"input {name!r} has shape {tuple(_np.shape(a))}, bound "
                    f"for {self._shapes[name]}; call reshape() for new "
                    "shapes (c_predict_api fixed-shape contract)")
            if not isinstance(a, NDArray):
                import jax.numpy as jnp
                a = NDArray(jnp.asarray(_np.asarray(a, self._dtype)),
                            self._ctx)
            feed[name] = a
        outs = self._ex.forward(is_train=False, **feed)
        res = [o.asnumpy() for o in outs]
        return res[0] if len(res) == 1 else res

    __call__ = predict

    @property
    def output_names(self) -> List[str]:
        return self._sym.list_outputs()

    @property
    def input_names(self) -> List[str]:
        return list(self._input_names)


def _selftest() -> int:
    """`python -m mxnet_tpu.predict model-prefix N C H W` smoke entry."""
    import sys
    import time
    prefix = sys.argv[1]
    shape = tuple(int(s) for s in sys.argv[2:]) or (1, 3, 224, 224)
    t0 = time.perf_counter()
    p = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                  input_shapes={"data": shape})
    t1 = time.perf_counter()
    out = p.predict(_np.zeros(shape, _np.float32))
    t2 = time.perf_counter()
    print(f"bind+compile {t1 - t0:.2f}s, predict {t2 - t1 :.4f}s, "
          f"out shape {getattr(out, 'shape', [o.shape for o in out])}")
    return 0


if __name__ == "__main__":
    raise SystemExit(_selftest())
