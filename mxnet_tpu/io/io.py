"""Data iterators (reference src/io/* + python/mxnet/io/io.py).

The reference's C++ pipeline (RecordIO parse → decode → augment → batch →
PrefetcherIter double-buffer) maps to: numpy-producer thread(s) → host batch →
async `jax.device_put` (PJRT overlaps H2D with compute) → NDArray. A
background prefetch thread gives the double-buffering (`PrefetchingIter`).
"""
from __future__ import annotations

import gzip
import os
import queue
import struct
import threading
from collections import namedtuple
from typing import List, Optional

import numpy as _np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import NDArray, array


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout):
        return 0 if layout is None else layout.find("N")


class DataBatch:
    def __init__(self, data, label=None, pad=0, index=None, bucket_key=None,
                 provide_data=None, provide_label=None):
        self.data = data if isinstance(data, (list, tuple)) or data is None else [data]
        self.label = label if isinstance(label, (list, tuple)) or label is None else [label]
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        ds = [d.shape for d in self.data] if self.data else []
        ls = [l.shape for l in self.label] if self.label else []
        return f"DataBatch: data shapes: {ds} label shapes: {ls}"


class DataIter:
    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(), self.getpad(),
                             self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


class NDArrayIter(DataIter):
    """In-memory iterator (reference python/mxnet/io/io.py NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label",
                 ctx: Optional[Context] = None):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = _np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.num_data = self.idx.shape[0]
        self.cursor = -batch_size
        self._ctx = ctx or current_context()
        self._cache_data = None
        if last_batch_handle == "discard":
            self.num_data = (self.num_data // batch_size) * batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self.idx)
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _take(self, arrays):
        end = self.cursor + self.batch_size
        if end <= self.num_data:
            sel = self.idx[self.cursor:end]
        else:
            if self.last_batch_handle == "roll_over":
                sel = _np.concatenate([self.idx[self.cursor:],
                                       self.idx[:end - self.num_data]])
            else:  # pad
                sel = _np.concatenate([self.idx[self.cursor:],
                                       self.idx[:end - self.num_data]])
        return [array(v[sel], ctx=self._ctx, dtype=v.dtype) for _, v in arrays]

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        end = self.cursor + self.batch_size
        if end > self.num_data and self.last_batch_handle == "pad":
            return end - self.num_data
        return 0


def _init_data(data, allow_empty, default_name):
    if data is None:
        if not allow_empty:
            raise MXNetError("data cannot be None")
        return []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        out = [(f"{default_name}{'_' + str(i) if i else ''}", d) for i, d in enumerate(data)]
    elif isinstance(data, dict):
        out = list(data.items())
    else:
        raise MXNetError(f"unsupported data type {type(data)}")
    return [(k, v.asnumpy() if isinstance(v, NDArray) else _np.asarray(v))
            for k, v in out]


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch (reference io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label


def _bounded_put(q: "queue.Queue", item, stop: threading.Event) -> bool:
    """put() that gives up once the consumer signalled stop — a blocking
    put into a full queue whose consumer left is a permanent thread leak
    (the reference prefetcher's shutdown path drains before joining for
    the same reason)."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


class PrefetchingIter(DataIter):
    """Background-thread prefetch (reference src/io/iter_prefetcher.h:47)."""

    def __init__(self, iters, rename_data=None, rename_label=None, prefetch_depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self._prefetch_depth = max(int(prefetch_depth), 1)
        self._q: queue.Queue = queue.Queue(maxsize=self._prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self._start()

    @property
    def provide_data(self):
        return sum([i.provide_data for i in self.iters], [])

    @property
    def provide_label(self):
        return sum([i.provide_label for i in self.iters], [])

    def _start(self):
        stop, q = self._stop, self._q

        def worker():
            try:
                while not stop.is_set():
                    batches = []
                    try:
                        for it in self.iters:
                            batches.append(it.next())
                    except StopIteration:
                        _bounded_put(q, None, stop)
                        return
                    data = sum([b.data for b in batches], [])
                    label = sum([(b.label or []) for b in batches], [])
                    if not _bounded_put(q, DataBatch(data, label,
                                                     batches[0].pad), stop):
                        return
            except Exception as e:  # propagate to consumer
                _bounded_put(q, e, stop)
        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="mx-io-prefetch")
        self._thread.start()

    def reset(self):
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        for it in self.iters:
            it.reset()
        self._stop = threading.Event()
        # regression (ISSUE 5 satellite): the rebuilt queue must keep the
        # constructor's prefetch_depth, not a hardcoded maxsize
        self._q = queue.Queue(maxsize=self._prefetch_depth)
        self._start()

    def next(self):
        item = self._q.get()
        if item is None:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item

    def iter_next(self):
        try:
            self._peek = self.next()
            return True
        except StopIteration:
            return False


class MNISTIter(DataIter):
    """MNIST idx-format reader (reference src/io/iter_mnist.cc:80).
    Generates a deterministic synthetic set when files are absent so tests
    and examples run hermetically."""

    def __init__(self, image="train-images-idx3-ubyte", label="train-labels-idx1-ubyte",
                 batch_size=128, shuffle=True, flat=False, seed=0, silent=True,
                 num_parts=1, part_index=0, ctx=None, synthetic_size=2048):
        super().__init__(batch_size)
        if os.path.exists(image) and os.path.exists(label):
            imgs = self._read_idx(image)
            labs = self._read_idx(label)
        else:
            rng = _np.random.RandomState(seed)
            # class-dependent SPATIALLY-STRUCTURED means (low-frequency 4x4
            # patterns upsampled to 28x28): per-pixel noise patterns would be
            # learnable by a linear probe but invisible to conv+pool nets,
            # which need large coherent regions
            labs = rng.randint(0, 10, size=(synthetic_size,)).astype("uint8")
            base4 = rng.rand(10, 4, 4).astype("float32")
            base = _np.kron(base4, _np.ones((7, 7), "float32"))
            imgs = (base[labs] * 255 * 0.5 +
                    rng.rand(synthetic_size, 28, 28) * 127).astype("uint8")
        if num_parts > 1:
            imgs = imgs[part_index::num_parts]
            labs = labs[part_index::num_parts]
        x = imgs.astype("float32") / 255.0
        x = x.reshape(len(x), -1) if flat else x.reshape(len(x), 1, 28, 28)
        self._inner = NDArrayIter(x, labs.astype("float32"), batch_size,
                                  shuffle=shuffle, ctx=ctx)

    @staticmethod
    def _read_idx(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic = struct.unpack(">I", f.read(4))[0]
            ndim = magic & 0xFF
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            return _np.frombuffer(f.read(), dtype=_np.uint8).reshape(dims)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


class CSVIter(DataIter):
    """CSV reader (reference src/io/iter_csv.cc:164)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, ctx=None):
        super().__init__(batch_size)
        data = _np.loadtxt(data_csv, delimiter=",", dtype="float32")
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype="float32")
            label = label.reshape((-1,) + tuple(label_shape))
        self._inner = NDArrayIter(data, label, batch_size, ctx=ctx,
                                  last_batch_handle="roll_over" if round_batch else "discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


class ImageRecordIter(DataIter):
    """RecordIO image pipeline (reference src/io/iter_image_recordio_2.cc).
    Backed by the native recordio reader (mxnet_tpu/recordio); decode+augment
    run in worker threads feeding a prefetch queue."""

    def __init__(self, path_imgrec=None, data_shape=(3, 224, 224), batch_size=1,
                 label_width=1, shuffle=False, rand_crop=False, rand_mirror=False,
                 mean_r=0, mean_g=0, mean_b=0, std_r=1, std_g=1, std_b=1,
                 preprocess_threads=4, prefetch_buffer=4, ctx=None,
                 synthetic=False, synthetic_size=256, seed=0, resize=0,
                 brightness=0, contrast=0, saturation=0, pca_noise=0,
                 rand_resize=False, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self._ctx = ctx or current_context()
        self._mean = _np.asarray([mean_r, mean_g, mean_b],
                                 "float32").reshape(3, 1, 1)
        self._std = _np.asarray([std_r or 1, std_g or 1, std_b or 1],
                                "float32").reshape(3, 1, 1)
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._label_width = label_width
        # per-worker-thread RNG (reference iter_image_recordio_2.cc seeds
        # one prnd per decode thread): RandomState is not thread-safe, so
        # each pool worker gets its own stream derived from `seed` —
        # reproducible per worker, order across workers is scheduling-
        # dependent exactly like the reference's threaded pipeline
        self._seed = seed
        self._main_rng = None
        self._epoch_ctr = 0
        self._inner = None
        self._reader = None
        self._cached = None
        self._nthreads = max(int(preprocess_threads), 1)
        self._prefetch = max(int(prefetch_buffer), 1)
        self._producer = None
        self._batch_q = None
        self._stop_flag = None
        # encoded-image augmenter pipeline (reference image_aug_default.cc
        # flags): resize-short -> random/center crop -> flip -> color jitter
        # -> PCA lighting; normalization stays in _augment (shared with the
        # raw-CHW payload path)
        from .. import image as _img
        c, h, w = self.data_shape
        if resize == 0 and (rand_crop or rand_resize):
            resize = max(h, w) + max(h, w) // 8
        self._auglist = _img.CreateAugmenter(
            (c, h, w), resize=resize, rand_crop=rand_crop,
            rand_resize=rand_resize, rand_mirror=rand_mirror,
            brightness=brightness, contrast=contrast, saturation=saturation,
            pca_noise=pca_noise)
        # native C++ JPEG pipeline (src/native/jpegdec.cc — the reference
        # iter_image_recordio_2.cc threaded decode): decode + resize-short
        # + crop + mirror + normalize for a whole batch in ONE GIL-free
        # call. Engaged when the requested augmentations are exactly the
        # standard geometry (photometric jitter / RandomSizedCrop keep the
        # Python path); non-JPEG payloads fall back per record.
        self._native_jpeg = None
        if c == 3 and not rand_resize and not (brightness or contrast or
                                               saturation or pca_noise):
            try:
                from .. import native as _nat
                if _nat.jpeg_available():
                    self._native_jpeg = _nat.NativeJpegDecoder(
                        h, w, resize_short=resize,
                        rand_crop=bool(rand_crop),
                        rand_mirror=bool(rand_mirror), seed=seed,
                        nthreads=self._nthreads,
                        mean=[float(m) for m in self._mean.ravel()],
                        std=[float(s) for s in self._std.ravel()])
            except Exception:
                self._native_jpeg = None
        if path_imgrec and not synthetic:
            if not os.path.exists(path_imgrec):
                raise MXNetError(f"record file not found: {path_imgrec}")
            # native C++ prefetch reader; payloads may be encoded images
            # (decoded via cv2 when available) or raw arrays whose byte size
            # matches data_shape (uint8 or float32), the cv2-free path
            from ..recordio import NativeRecordReader, native_available
            if native_available():
                self._reader = NativeRecordReader(path_imgrec, shuffle=shuffle,
                                                  seed=seed)
            else:
                self._reader = _PyRecordStream(path_imgrec, shuffle=shuffle,
                                               seed=seed)
            return
        # synthetic benchmark mode (reference example/image-classification
        # README 'benchmark with synthetic data')
        rng = _np.random.RandomState(0)
        self._data = rng.rand(synthetic_size, *self.data_shape).astype("float32")
        self._label = rng.randint(0, 1000, size=(synthetic_size,)).astype("float32")
        self._inner = NDArrayIter(self._data, self._label, batch_size,
                                  shuffle=shuffle, ctx=self._ctx)

    def _decode(self, payload: bytes) -> _np.ndarray:
        """payload -> CHW float32, augmented. Raw CHW uint8/float32 buffers
        pass straight to the crop/mirror path; encoded images run the full
        augmenter pipeline (decode -> resize -> crop -> flip -> jitter)."""
        c, h, w = self.data_shape
        n_u8 = c * h * w
        if len(payload) == n_u8:
            img = _np.frombuffer(payload, _np.uint8).reshape(self.data_shape)
            return img.astype(_np.float32), True
        if len(payload) == n_u8 * 4:
            return _np.frombuffer(payload, _np.float32).reshape(
                self.data_shape).copy(), True
        from .. import image as _img
        try:
            hwc = _img.imdecode(_np.frombuffer(payload, _np.uint8))
        except Exception as e:
            raise MXNetError(
                "record payload is neither a raw CHW uint8/float32 buffer "
                f"matching data_shape {self.data_shape} nor decodable as a "
                f"compressed image ({e})")
        arr = hwc.asnumpy().astype(_np.float32)
        for aug in self._auglist:
            arr = aug(arr)
        arr = _np.asarray(arr, _np.float32)
        if arr.shape[:2] != (h, w):
            # source smaller than the crop target: force exact size (the
            # reference's C++ default augmenter also resizes as a last step)
            from .. import image as _img
            arr = _img.imresize(arr, w, h).asnumpy().astype(_np.float32)
        return _np.moveaxis(arr, -1, 0), False

    @property
    def _rng(self):
        # pool workers carry an rng attached by the pool initializer
        # (stable per-worker index, reference seeds prnd per worker index
        # too); non-pool callers (ImageDetRecordIter's synchronous path)
        # share one deterministic per-iterator stream
        rng = getattr(threading.current_thread(), "_mx_io_rng", None)
        if rng is None:
            rng = self._main_rng
            if rng is None:
                rng = self._main_rng = _np.random.RandomState(
                    self._seed % (2 ** 31))
        return rng

    def _augment(self, img: _np.ndarray, raw: bool) -> _np.ndarray:
        """Crop/mirror for raw-CHW payloads (encoded images get those from
        the augmenter pipeline inside _decode), then mean/std normalize."""
        c, h, w = self.data_shape
        if raw:
            if img.shape[1:] != (h, w):
                # crop: random position with rand_crop, center otherwise
                ih, iw = img.shape[1], img.shape[2]
                if self._rand_crop:
                    y0 = self._rng.randint(0, max(ih - h, 0) + 1)
                    x0 = self._rng.randint(0, max(iw - w, 0) + 1)
                else:
                    y0, x0 = max(ih - h, 0) // 2, max(iw - w, 0) // 2
                img = img[:, y0:y0 + h, x0:x0 + w]
            if self._rand_mirror and self._rng.rand() < 0.5:
                img = img[:, :, ::-1]
        img = (img - self._mean) / self._std
        return _np.ascontiguousarray(img)

    def _label_of(self, header):
        lab = header.label
        return float(lab) if _np.isscalar(lab) else _np.asarray(
            lab, "float32")[:self._label_width]

    def _process_one(self, rec):
        from ..recordio import unpack
        header, payload = unpack(rec)
        img, raw = self._decode(payload)
        return self._augment(img, raw), self._label_of(header)

    def _produce(self, stop, q):
        """Producer thread: read records serially, decode+augment on a
        thread pool (reference iter_image_recordio_2.cc:880 threaded
        pipeline), assemble batches in order, feed the prefetch queue."""
        import concurrent.futures as cf
        from ..ndarray import array
        # worker seeds are handed out per POOL via the initializer, mixed
        # with an epoch counter: run-to-run a fixed seed reproduces the
        # same streams, while successive epochs draw DIFFERENT augmentation
        # randomness (reference threads advance their prnd across epochs).
        # A zombie thread from a timed-out previous pool keeps its own rng
        # (attached to the thread object) without consuming a new index.
        lock = threading.Lock()
        nxt = [0]
        epoch = self._epoch_ctr
        self._epoch_ctr += 1
        seed0 = self._seed

        def _init_worker():
            with lock:
                widx = nxt[0]
                nxt[0] += 1
            threading.current_thread()._mx_io_rng = _np.random.RandomState(
                (seed0 + 1000003 * epoch + widx) % (2 ** 31))
        try:
            with cf.ThreadPoolExecutor(self._nthreads,
                                       initializer=_init_worker) as pool:
                while not stop.is_set():
                    recs = []
                    while len(recs) < self.batch_size:
                        rec = self._reader.next()
                        if rec is None:
                            break
                        recs.append(rec)
                    if not recs:
                        _bounded_put(q, None, stop)
                        return
                    xs = ys = None
                    if self._native_jpeg is not None:
                        xs, ys = self._native_batch(recs)
                    if xs is None:
                        results = list(pool.map(self._process_one, recs))
                        xs = [r[0] for r in results]
                        ys = [r[1] for r in results]
                    pad = self.batch_size - len(xs)
                    if pad:
                        xs += [xs[-1]] * pad
                        ys += [ys[-1]] * pad
                    batch = DataBatch(data=[array(_np.stack(xs))],
                                      label=[array(_np.asarray(ys, "float32"))],
                                      pad=pad)
                    if not _bounded_put(q, batch, stop):
                        return
        except Exception as e:  # surface errors at next(); bounded so an
            # interrupted epoch (full queue, consumer gone) can't wedge
            # the thread in a blocking put forever
            _bounded_put(q, e, stop)

    def _native_batch(self, recs):
        """Decode a record batch through the C++ JPEG pipeline. Returns
        (xs, ys) or (None, None) when the batch is not all-JPEG (caller
        falls back to the Python pool path). Corrupt JPEGs fall back per
        record on the already-unpacked payload."""
        from ..recordio import unpack
        headers, payloads = [], []
        for rec in recs:
            h, p = unpack(rec)
            if not p.startswith(b"\xff\xd8"):
                return None, None
            headers.append(h)
            payloads.append(p)
        out, ok = self._native_jpeg.decode_batch(payloads)
        xs = list(out)
        for i, good in enumerate(ok):
            if not good:  # corrupt record: Python path raises a clear error
                img, raw = self._decode(payloads[i])
                xs[i] = self._augment(img, raw)
        return xs, [self._label_of(h) for h in headers]

    def _ensure_producer(self):
        if self._producer is None or not self._producer.is_alive():
            if self._batch_q is None:
                self._stop_flag = threading.Event()
                self._batch_q = queue.Queue(maxsize=self._prefetch)
                self._producer = threading.Thread(
                    target=self._produce,
                    args=(self._stop_flag, self._batch_q), daemon=True,
                    name="mx-io-producer")
                self._producer.start()

    def _next_record_batch(self):
        self._ensure_producer()
        item = self._batch_q.get()
        if isinstance(item, Exception):
            # clear the dead producer so a retrying caller restarts it
            # instead of blocking on an empty queue forever
            self._batch_q = None
            self._producer = None
            raise item
        if item is None:
            self._batch_q = None  # producer finished; reset() restarts it
            self._producer = None
        return item

    def _stop_producer(self):
        if self._producer is not None:
            self._stop_flag.set()
            # drain -> join -> drain: the producer may complete one more
            # put between our drain and its stop-flag check; a second
            # round guarantees it unblocks and the join lands
            for _ in range(2):
                try:
                    while True:
                        self._batch_q.get_nowait()
                except queue.Empty:
                    pass
                self._producer.join(timeout=5)
                if not self._producer.is_alive():
                    break
            self._producer = None
        self._batch_q = None

    def __del__(self):
        # interrupted epochs must not leak the decode/prefetch thread
        try:
            if getattr(self, "_producer", None) is not None:
                self._stop_producer()
        except Exception:
            pass

    @property
    def provide_data(self):
        if self._inner is not None:
            return self._inner.provide_data
        return [("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        if self._inner is not None:
            return self._inner.provide_label
        shp = (self.batch_size,) if self._label_width == 1 \
            else (self.batch_size, self._label_width)
        return [("softmax_label", shp)]

    def reset(self):
        if self._inner is not None:
            self._inner.reset()
        else:
            self._stop_producer()
            self._cached = None
            self._reader.reset()

    def next(self):
        if self._inner is not None:
            return self._inner.next()
        if self._cached is not None:
            batch, self._cached = self._cached, None
            return batch
        batch = self._next_record_batch()
        if batch is None:
            raise StopIteration
        return batch

    def iter_next(self):
        if self._inner is not None:
            return self._inner.iter_next()
        if self._cached is not None:
            return True
        self._cached = self._next_record_batch()
        return self._cached is not None

    def getdata(self):
        if self._inner is not None:
            return self._inner.getdata()
        return self._cached.data

    def getlabel(self):
        if self._inner is not None:
            return self._inner.getlabel()
        return self._cached.label

    def getpad(self):
        if self._inner is not None:
            return self._inner.getpad()
        return self._cached.pad if self._cached is not None else 0


class _PyRecordStream:
    """Pure-python fallback with the NativeRecordReader surface; shuffle is
    an offset permutation re-drawn each epoch."""

    def __init__(self, path, shuffle=False, seed=0):
        from ..recordio import MXRecordIO
        self._rec = MXRecordIO(path, "r")
        self._shuffle = shuffle
        self._rng = _np.random.RandomState(seed)
        self._offsets = None
        self._order = []
        self._cursor = 0
        if shuffle:
            self._scan_offsets()
            self._reshuffle()

    def _scan_offsets(self):
        offs = []
        while True:
            pos = self._rec.tell()
            if self._rec.read() is None:
                break
            offs.append(pos)
        self._offsets = offs
        self._rec.reset()

    def _reshuffle(self):
        self._order = self._rng.permutation(len(self._offsets)).tolist()
        self._cursor = 0

    def next(self):
        if not self._shuffle:
            return self._rec.read()
        if self._cursor >= len(self._order):
            return None
        self._rec.seek(self._offsets[self._order[self._cursor]])
        self._cursor += 1
        return self._rec.read()

    def reset(self):
        self._rec.reset()
        if self._shuffle:
            self._reshuffle()


class LibSVMIter(DataIter):
    """LibSVM text reader yielding CSR data batches (reference
    src/io/iter_libsvm.cc:67). Line format: ``label[,label...] idx:val ...``;
    when ``label_libsvm`` is given, labels are read as CSR from a second
    libsvm file (multi-label), matching the reference's dual-parser mode.

    Data batches are CSRNDArray (dense-backed here — SURVEY.md §7 hard-part
    4: XLA has no dynamic sparsity, so CSR is an API-level view; the chip
    consumes the dense block)."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 num_parts=1, part_index=0, ctx=None, **kwargs):
        super().__init__(batch_size)
        if isinstance(data_shape, int):
            data_shape = (data_shape,)
        if isinstance(label_shape, int):
            label_shape = (label_shape,)
        if len(tuple(data_shape)) != 1:
            raise MXNetError("LibSVMIter: data_shape must be 1-D "
                             "(feature dimension), like the reference")
        self.data_shape = tuple(data_shape)
        self.label_shape = tuple(label_shape)
        self._ctx = ctx or current_context()
        self._round_batch = round_batch
        rows, labels = self._parse(data_libsvm, self.data_shape[0])
        if label_libsvm:
            if int(_np.prod(self.label_shape)) <= 1:
                raise MXNetError("label_shape must be >1 with label_libsvm "
                                 "(iter_libsvm.cc:86)")
            lab_rows, _ = self._parse(label_libsvm, self.label_shape[0])
            self._label = lab_rows
            self._label_csr = True
        else:
            if int(_np.prod(self.label_shape)) != 1:
                raise MXNetError("label_shape is expected to be (1,) when "
                                 "label_libsvm is NULL (iter_libsvm.cc:88)")
            self._label = _np.asarray(labels, "float32")
            self._label_csr = False
        if num_parts > 1:
            rows = rows[part_index::num_parts]
            self._label = self._label[part_index::num_parts]
        self._data = rows
        self._n = len(rows)
        self._cur = 0

    @staticmethod
    def _parse(path, width):
        """-> (dense rows [n, width] float32, first-label column)."""
        rows, labels = [], []
        with open(path) as fin:
            for line in fin:
                line = line.strip()
                if not line:
                    continue
                parts = line.split()
                labels.append(float(parts[0].split(",")[0]))
                row = _np.zeros((width,), "float32")
                for tok in parts[1:]:
                    if ":" not in tok:
                        continue
                    i, v = tok.split(":")
                    row[int(i)] = float(v)
                rows.append(row)
        return _np.stack(rows) if rows else _np.zeros((0, width), "float32"), \
            labels

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shp = (self.batch_size,) + (self.label_shape if self._label_csr
                                    else ())
        return [DataDesc("softmax_label", shp)]

    def reset(self):
        self._cur = 0

    def iter_next(self):
        return self._cur < self._n

    def next(self):
        from ..ndarray.sparse import csr_matrix as _csr
        def _csr_batch(a, ctx):
            return _csr(a, ctx=ctx)
        if self._cur >= self._n:
            raise StopIteration
        end = min(self._cur + self.batch_size, self._n)
        xs = self._data[self._cur:end]
        ys = self._label[self._cur:end]
        pad = self.batch_size - (end - self._cur)
        if pad:
            if self._round_batch and self._n >= self.batch_size:
                # wrap around to the beginning, reference round_batch
                xs = _np.concatenate([xs, self._data[:pad]])
                ys = _np.concatenate([ys, self._label[:pad]])
                pad = 0
            else:
                xs = _np.concatenate([xs, _np.repeat(xs[-1:], pad, 0)])
                ys = _np.concatenate([ys, _np.repeat(ys[-1:], pad, 0)])
        self._cur = end
        data = _csr_batch(xs, self._ctx)
        label = _csr_batch(ys, self._ctx) if self._label_csr else \
            array(ys, ctx=self._ctx)
        return DataBatch(data=[data], label=[label], pad=pad)


class ImageDetRecordIter(DataIter):
    """Detection RecordIO iterator (reference
    src/io/iter_image_det_recordio.cc). Records carry variable-length
    object labels ``[header_width, object_width, extras..., obj0..., ...]``;
    each batch pads every sample's label block to the widest in the batch
    (or ``label_pad_width``) with ``label_pad_value``, exactly like the
    reference, so SSD-style targets can be stacked densely."""

    def __init__(self, path_imgrec, data_shape=(3, 300, 300), batch_size=1,
                 shuffle=False, label_pad_width=0, label_pad_value=-1.0,
                 mean_r=0, mean_g=0, mean_b=0, std_r=1, std_g=1, std_b=1,
                 rand_mirror=False, preprocess_threads=4, prefetch_buffer=4,
                 seed=0, ctx=None, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self._ctx = ctx or current_context()
        self._pad_width = int(label_pad_width)
        self._pad_value = float(label_pad_value)
        # reuse ImageRecordIter's reader/decode/augment machinery but read
        # synchronously — detection labels are ragged, so batching happens
        # here (rand_mirror is intentionally OFF: flipping would need the
        # box coordinates rewritten; augment at training level instead)
        bad = sorted(k for k in ("rand_crop", "rand_resize", "resize",
                                 "max_rotate_angle", "max_shear_ratio")
                     if kwargs.get(k))
        if bad:
            raise MXNetError(
                "ImageDetRecordIter does not support geometric augmentation "
                f"({', '.join(bad)}): box labels would not be rewritten to "
                "match (reference DefaultImageDetAugmenter adjusts them; "
                "here augment at training level instead)")
        self._inner = ImageRecordIter(
            path_imgrec=path_imgrec, data_shape=data_shape,
            batch_size=batch_size, shuffle=shuffle, rand_mirror=False,
            mean_r=mean_r, mean_g=mean_g, mean_b=mean_b,
            std_r=std_r, std_g=std_g, std_b=std_b,
            preprocess_threads=preprocess_threads,
            prefetch_buffer=prefetch_buffer, seed=seed, ctx=ctx, **kwargs)
        # encoded det images are RESIZED to the target, never cropped:
        # a pure resize keeps normalized [0,1] box coordinates valid, a
        # center/random crop would silently invalidate them. Photometric
        # augmenters the caller requested (brightness/contrast/...) are
        # box-safe and kept.
        from .. import image as _img
        c, h, w = self.data_shape
        photometric = (_img.CastAug, _img.BrightnessJitterAug,
                       _img.ContrastJitterAug, _img.SaturationJitterAug,
                       _img.HueJitterAug, _img.LightingAug,
                       _img.ColorNormalizeAug)
        self._inner._auglist = [_img.ForceResizeAug((w, h))] + [
            a for a in self._inner._auglist if isinstance(a, photometric)]
        self._cached = None

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        w = self._pad_width if self._pad_width else None
        return [DataDesc("label", (self.batch_size, w))]

    def reset(self):
        self._cached = None
        self._inner._reader.reset()

    def iter_next(self):
        if self._cached is None:
            self._cached = self._read_batch()
        return self._cached is not None

    def _read_batch(self):
        from ..recordio import unpack
        inner = self._inner
        xs, labs = [], []
        while len(xs) < self.batch_size:
            rec = inner._reader.next()
            if rec is None:
                break
            header, payload = unpack(rec)
            lab = _np.atleast_1d(_np.asarray(header.label, "float32"))
            img, raw = inner._decode(payload)
            xs.append(inner._augment(img, raw))
            labs.append(lab)
        if not xs:
            return None
        pad = self.batch_size - len(xs)
        if pad:
            xs += [xs[-1]] * pad
            labs += [labs[-1]] * pad
        width = max(max(len(r) for r in labs), self._pad_width)
        out = _np.full((len(labs), width), self._pad_value, "float32")
        for i, r in enumerate(labs):
            out[i, :len(r)] = r
        return DataBatch(data=[array(_np.stack(xs), ctx=self._ctx)],
                         label=[array(out, ctx=self._ctx)], pad=pad)

    def next(self):
        if self._cached is not None:
            b, self._cached = self._cached, None
            return b
        b = self._read_batch()
        if b is None:
            raise StopIteration
        return b
