from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, MNISTIter, CSVIter, ImageRecordIter)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MNISTIter", "CSVIter", "ImageRecordIter"]
