from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, MNISTIter, CSVIter, ImageRecordIter,
                 LibSVMIter, ImageDetRecordIter)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MNISTIter", "CSVIter", "ImageRecordIter",
           "LibSVMIter", "ImageDetRecordIter"]
