"""mxnet_tpu — a TPU-native deep learning framework with MXNet's capability
surface (the `incubator-mxnet_tpu` project).

Brand-new design for TPU/XLA (NOT a port): jax/XLA is the compute path, Pallas
for hot kernels, `jax.sharding` meshes for parallelism. The imperative
NDArray + autograd + Gluon API matches the reference (Laurawly/incubator-mxnet)
so users can switch; the mechanisms are described in SURVEY.md §7.

Quick start::

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon

    x = nd.random.uniform(shape=(32, 784), ctx=mx.tpu())
    net = gluon.nn.Dense(10)
    net.initialize(ctx=mx.tpu())
    with autograd.record():
        loss = gluon.loss.SoftmaxCrossEntropyLoss()(net(x), nd.zeros((32,)))
    loss.backward()
"""
def _maybe_init_distributed():
    """Join the jax.distributed cluster BEFORE any jax computation runs —
    jax refuses to initialize afterwards. tools/launch.py (the reference
    tools/launch.py analog) sets these env vars for each worker; a bare
    `import mxnet_tpu` in the worker then connects automatically (the
    coordinator replaces the reference's ps-lite scheduler rendezvous)."""
    import os
    coord = os.environ.get("MXNET_TPU_COORDINATOR")
    if coord is None and os.environ.get("DMLC_PS_ROOT_URI"):
        # reference-compatible env (docs/faq/distributed_training.md:260)
        coord = (os.environ["DMLC_PS_ROOT_URI"] + ":"
                 + os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
    n = int(os.environ.get("MXNET_TPU_NUM_WORKERS",
                           os.environ.get("DMLC_NUM_WORKER", "1")))
    if not coord or n <= 1:
        return
    import jax
    from ._dist_util import dist_client_active
    if dist_client_active():
        return  # already initialized by the caller
    if os.environ.get("MXNET_TPU_RANK_FROM_MPI"):
        rank = (os.environ.get("OMPI_COMM_WORLD_RANK")
                or os.environ.get("PMI_RANK") or "0")
    else:
        rank = os.environ.get("MXNET_TPU_RANK",
                              os.environ.get("DMLC_WORKER_ID"))
    try:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=n,
                                   process_id=int(rank or 0))
    except RuntimeError:
        pass  # jax already ran computations (interactive use) — kvstore
        #       creation will surface the error with context


_maybe_init_distributed()

from . import base
from .base import MXNetError
from .context import Context, cpu, gpu, tpu, num_gpus, num_tpus, current_context, cpu_pinned
from . import ops
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from .ndarray import NDArray

from . import symbol
from . import symbol as sym
from . import initializer
from . import optimizer
from . import lr_scheduler
from . import metric
from . import io
from . import gluon
from . import kvstore as kv
from . import kvstore
from . import parallel
from . import profiler
from . import runtime
from . import util
from . import test_utils
from . import image
from . import recordio
from . import contrib
from . import numpy as np
from . import numpy_extension as npx
from . import module
from . import model
from . import callback
from . import monitor
from . import operator
from . import visualization
from . import rtc
from . import library
from . import name
from . import attribute
from .attribute import AttrScope
from .model import FeedForward
from .monitor import Monitor

from .util import is_np_shape, is_np_array, set_np, reset_np

__version__ = "1.0.0.dev0"

init = gluon.init  # alias: mx.init.Xavier() etc.


def __getattr__(name):
    if name == "checkpoint":
        # lazy: orbax costs ~2.6 s to import; only checkpoint users pay it
        import importlib
        mod = importlib.import_module(".checkpoint", __name__)
        globals()["checkpoint"] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu' has no attribute '{name}'")


def waitall():
    ndarray.waitall()
