"""mxnet_tpu — a TPU-native deep learning framework with MXNet's capability
surface (the `incubator-mxnet_tpu` project).

Brand-new design for TPU/XLA (NOT a port): jax/XLA is the compute path, Pallas
for hot kernels, `jax.sharding` meshes for parallelism. The imperative
NDArray + autograd + Gluon API matches the reference (Laurawly/incubator-mxnet)
so users can switch; the mechanisms are described in SURVEY.md §7.

Quick start::

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon

    x = nd.random.uniform(shape=(32, 784), ctx=mx.tpu())
    net = gluon.nn.Dense(10)
    net.initialize(ctx=mx.tpu())
    with autograd.record():
        loss = gluon.loss.SoftmaxCrossEntropyLoss()(net(x), nd.zeros((32,)))
    loss.backward()
"""
def _maybe_init_distributed():
    """Join the jax.distributed cluster BEFORE any jax computation runs —
    jax refuses to initialize afterwards. tools/launch.py (the reference
    tools/launch.py analog) sets these env vars for each worker; a bare
    `import mxnet_tpu` in the worker then connects automatically (the
    coordinator replaces the reference's ps-lite scheduler rendezvous)."""
    import os
    coord = os.environ.get("MXNET_TPU_COORDINATOR")
    if coord is None and os.environ.get("DMLC_PS_ROOT_URI"):
        # reference-compatible env (docs/faq/distributed_training.md:260)
        coord = (os.environ["DMLC_PS_ROOT_URI"] + ":"
                 + os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
    n = int(os.environ.get("MXNET_TPU_NUM_WORKERS",
                           os.environ.get("DMLC_NUM_WORKER", "1")))
    if not coord or n <= 1:
        return
    import jax
    from ._dist_util import dist_client_active
    if dist_client_active():
        return  # already initialized by the caller
    if os.environ.get("MXNET_TPU_RANK_FROM_MPI"):
        rank = (os.environ.get("OMPI_COMM_WORLD_RANK")
                or os.environ.get("PMI_RANK") or "0")
    else:
        rank = os.environ.get("MXNET_TPU_RANK",
                              os.environ.get("DMLC_WORKER_ID"))
    try:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=n,
                                   process_id=int(rank or 0))
    except RuntimeError:
        pass  # jax already ran computations (interactive use) — kvstore
        #       creation will surface the error with context


_maybe_init_distributed()

# Eager core: the light modules every entry point needs.
from . import base
from .base import MXNetError
from .context import Context, cpu, gpu, tpu, num_gpus, num_tpus, current_context, cpu_pinned
from . import ops
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from .ndarray import NDArray

from . import symbol
from . import symbol as sym
from . import util
from . import name
from . import attribute
from .attribute import AttrScope

from .util import is_np_shape, is_np_array, set_np, reset_np

__version__ = "1.0.0.dev0"

# Heavy subsystems load lazily (PEP 562): `mxnet_tpu.predict` — the minimal
# serving runtime (reference c_predict_api.h analog) — must come up WITHOUT
# pulling training machinery (optimizer/parallel/gluon/io/...), and every
# other entry point gets the import-time win for free. Attribute access
# (`mx.gluon`, `from mxnet_tpu import optimizer`) resolves identically to
# the old eager imports.
_LAZY_SUBMODULES = {
    "engine": ".engine",
    "initializer": ".initializer",
    "optimizer": ".optimizer",
    "lr_scheduler": ".lr_scheduler",
    "metric": ".metric",
    "io": ".io",
    "gluon": ".gluon",
    "kv": ".kvstore",
    "kvstore": ".kvstore",
    "parallel": ".parallel",
    "profiler": ".profiler",
    "telemetry": ".telemetry",
    "runtime": ".runtime",
    "test_utils": ".test_utils",
    "image": ".image",
    "recordio": ".recordio",
    "contrib": ".contrib",
    "np": ".numpy",
    "numpy": ".numpy",
    "npx": ".numpy_extension",
    "numpy_extension": ".numpy_extension",
    "module": ".module",
    "model": ".model",
    "callback": ".callback",
    "monitor": ".monitor",
    "operator": ".operator",
    "visualization": ".visualization",
    "rtc": ".rtc",
    "library": ".library",
    "checkpoint": ".checkpoint",   # orbax costs ~2.6 s to import
    "elastic": ".elastic",
    "faults": ".faults",
    "recipes": ".recipes",
    "predict": ".predict",
    "serving": ".serving",
    "sanitize": ".sanitize",
    "serialization": ".serialization",
}
_LAZY_ATTRS = {
    "FeedForward": (".model", "FeedForward"),
    "Monitor": (".monitor", "Monitor"),
}

# MXNET_TPU_SANITIZE=1 must arm the jax sanitizers (tracer-leak/NaN checks,
# per-step transfer guards) at import, so it can't stay behind the lazy
# table when the flag is set
import os as _os
if _os.environ.get("MXNET_TPU_SANITIZE", "").strip().lower() \
        not in ("", "0", "false", "off"):
    from . import sanitize  # noqa: F401


def __getattr__(name):
    import importlib
    if name in _LAZY_SUBMODULES:
        mod = importlib.import_module(_LAZY_SUBMODULES[name], __name__)
        globals()[name] = mod
        return mod
    if name in _LAZY_ATTRS:
        modname, attr = _LAZY_ATTRS[name]
        val = getattr(importlib.import_module(modname, __name__), attr)
        globals()[name] = val
        return val
    if name == "init":
        # alias: mx.init.Xavier() etc.
        val = importlib.import_module(".gluon", __name__).init
        globals()["init"] = val
        return val
    raise AttributeError(f"module 'mxnet_tpu' has no attribute '{name}'")


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY_SUBMODULES)
                      + list(_LAZY_ATTRS) + ["init"]))


def waitall():
    ndarray.waitall()
