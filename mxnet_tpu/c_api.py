"""Flat, handle-based procedural facade over the framework — the
`c_api`-shaped module boundary (reference include/mxnet/c_api.h, 3,245
lines of `MX*` entry points; SURVEY.md §7 asked to keep this seam).

Purpose: future non-python bindings (C/C++/Scala/Julia via cffi or the
CPython C API) talk to ONE flat surface of functions over opaque integer
handles — exactly how every reference frontend binds libmxnet.so. Nothing
here adds capability; it re-exposes the object API in the reference's
calling convention:

- handles are process-unique ints (`NDArrayHandle`, `SymbolHandle`,
  `ExecutorHandle`, `KVStoreHandle`), freed explicitly;
- every call returns 0 on success; failures raise MXNetError whose text
  is retrievable via `MXGetLastError()` (the reference's errno pattern);
- outputs are returned (pythonic) rather than written through pointers —
  a binding layer maps those to out-params mechanically.
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as _np

from .base import MXNetError

_lock = threading.Lock()
_handles: Dict[int, Any] = {}
_next_id = itertools.count(1)
_last_error = threading.local()


def _register(obj) -> int:
    with _lock:
        h = next(_next_id)
        _handles[h] = obj
    return h


def _get(handle: int):
    try:
        return _handles[handle]
    except KeyError:
        raise MXNetError(f"invalid handle {handle}") from None


def _api(fn):
    """Record failures for MXGetLastError, reference c_api error pattern."""
    import functools

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:
            _last_error.msg = str(e)
            raise
    return wrapper


def MXGetLastError() -> str:
    return getattr(_last_error, "msg", "")


def MXGetVersion() -> int:
    import re
    from . import __version__
    nums = re.findall(r"\d+", str(__version__))[:3] + ["0", "0", "0"]
    return int(nums[0]) * 10000 + int(nums[1]) * 100 + int(nums[2])


# -- NDArray ----------------------------------------------------------------

@_api
def MXNDArrayCreate(shape, dtype="float32", ctx=None) -> int:
    from .ndarray import zeros
    return _register(zeros(tuple(shape), dtype=dtype, ctx=ctx))


@_api
def MXNDArrayCreateFromNumpy(arr) -> int:
    from .ndarray import array
    a = _np.asarray(arr)
    return _register(array(a, dtype=str(a.dtype)))


@_api
def MXNDArrayFree(handle: int) -> int:
    with _lock:
        _handles.pop(handle, None)
    return 0


@_api
def MXNDArrayGetShape(handle: int) -> Tuple[int, ...]:
    return tuple(_get(handle).shape)


@_api
def MXNDArrayGetDType(handle: int) -> str:
    return str(_get(handle).dtype)


@_api
def MXNDArraySyncCopyToCPU(handle: int) -> _np.ndarray:
    return _get(handle).asnumpy()


@_api
def MXNDArraySyncCopyFromCPU(handle: int, arr) -> int:
    from .ndarray import array
    nd = _get(handle)
    nd._set_data(array(_np.asarray(arr), dtype=str(nd.dtype))._data)
    return 0


@_api
def MXNDArrayWaitToRead(handle: int) -> int:
    _get(handle).wait_to_read()
    return 0


@_api
def MXNDArrayWaitAll() -> int:
    from .ndarray import waitall
    waitall()
    return 0


@_api
def MXNDArraySave(fname: str, handles: List[int], keys: List[str]) -> int:
    from .serialization import save_ndarrays
    save_ndarrays(fname, {k: _get(h) for k, h in zip(keys, handles)})
    return 0


@_api
def MXNDArrayLoad(fname: str) -> Tuple[List[str], List[int]]:
    from .serialization import load_ndarrays
    loaded = load_ndarrays(fname)
    return list(loaded.keys()), [_register(v) for v in loaded.values()]


# -- Operator invocation (MXImperativeInvoke) -------------------------------

@_api
def MXListAllOpNames() -> List[str]:
    from .ops import registry
    return sorted(registry.all_ops())


@_api
def MXImperativeInvoke(op_name: str, in_handles: List[int],
                       **params) -> List[int]:
    """reference c_api.cc MXImperativeInvokeEx: run a registered op on
    NDArray handles, returning output handles."""
    from . import ndarray as nd_mod
    fn = getattr(nd_mod, op_name, None)
    if fn is None:
        raise MXNetError(f"unknown operator {op_name!r}")
    out = fn(*[_get(h) for h in in_handles], **params)
    outs = out if isinstance(out, (list, tuple)) else [out]
    return [_register(o) for o in outs]


# -- Symbol -----------------------------------------------------------------

@_api
def MXSymbolCreateVariable(name: str) -> int:
    from . import symbol as sym_mod
    return _register(sym_mod.Variable(name))


@_api
def MXSymbolCreateAtomicSymbol(op_name: str, in_handles: List[int],
                               name: Optional[str] = None, **params) -> int:
    from . import symbol as sym_mod
    fn = getattr(sym_mod, op_name, None)
    if fn is None:
        raise MXNetError(f"unknown operator {op_name!r}")
    if name is not None:
        params = dict(params, name=name)
    return _register(fn(*[_get(h) for h in in_handles], **params))


@_api
def MXSymbolSaveToJSON(handle: int) -> str:
    return _get(handle).tojson()


@_api
def MXSymbolCreateFromJSON(json_str: str) -> int:
    from .symbol.symbol import load_json
    return _register(load_json(json_str))


@_api
def MXSymbolListArguments(handle: int) -> List[str]:
    return list(_get(handle).list_arguments())


@_api
def MXSymbolListOutputs(handle: int) -> List[str]:
    return list(_get(handle).list_outputs())


@_api
def MXSymbolInferShape(handle: int, **kwargs):
    return _get(handle).infer_shape(**kwargs)


@_api
def MXSymbolFree(handle: int) -> int:
    return MXNDArrayFree(handle)


# -- Executor ---------------------------------------------------------------

@_api
def MXExecutorBind(sym_handle: int, arg_handles: Dict[str, int],
                   ctx=None) -> int:
    sym = _get(sym_handle)
    binds = {k: _get(h) for k, h in arg_handles.items()}
    return _register(sym.bind(ctx, binds))


@_api
def MXExecutorForward(handle: int, is_train: bool = False) -> List[int]:
    outs = _get(handle).forward(is_train=is_train)
    return [_register(o) for o in outs]


@_api
def MXExecutorBackward(handle: int, out_grad_handles: List[int]) -> int:
    _get(handle).backward([_get(h) for h in out_grad_handles])
    return 0


@_api
def MXExecutorFree(handle: int) -> int:
    return MXNDArrayFree(handle)


# -- KVStore ----------------------------------------------------------------

@_api
def MXKVStoreCreate(kind: str = "local") -> int:
    from . import kvstore as kvs
    return _register(kvs.create(kind))


def _kv_vals(keys, handles):
    if isinstance(handles, (list, tuple)):
        vals = [_get(h) for h in handles]
        # scalar key with a single handle arrives as a 1-list from bindings
        if not isinstance(keys, (list, tuple)) and len(vals) == 1:
            return vals[0]
        return vals
    return _get(handles)


@_api
def MXKVStoreInit(handle: int, keys, value_handles) -> int:
    _get(handle).init(keys, _kv_vals(keys, value_handles))
    return 0


@_api
def MXKVStorePush(handle: int, keys, value_handles) -> int:
    _get(handle).push(keys, _kv_vals(keys, value_handles))
    return 0


@_api
def MXKVStorePull(handle: int, keys, out_handles) -> int:
    _get(handle).pull(keys, out=_kv_vals(keys, out_handles))
    return 0


@_api
def MXKVStoreFree(handle: int) -> int:
    return MXNDArrayFree(handle)


# -- Misc -------------------------------------------------------------------

@_api
def MXRandomSeed(seed: int) -> int:
    from . import random as rnd
    rnd.seed(seed)
    return 0


@_api
def MXLibInfoFeatures() -> List[str]:
    from .runtime import feature_list
    return [f.name for f in feature_list()]
