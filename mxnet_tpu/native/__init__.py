"""ctypes bindings for the native C++ runtime (src/native/).

The reference framework's IO/runtime layers are C++ (SURVEY.md §2.1:
src/io/ 6.6 kLoC, dmlc recordio); this package binds the TPU framework's
C++ equivalents. The shared library is compiled on first use with g++ and
cached next to the sources (no external deps, ~1 s); every consumer falls
back to the pure-Python path when the toolchain is unavailable.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as _np

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
_SRC = os.path.join(_REPO_ROOT, "src", "native", "recordio.cc")
_SRC_JPEG = os.path.join(_REPO_ROOT, "src", "native", "jpegdec.cc")
_LIB_PATH = os.path.join(_REPO_ROOT, "src", "native", "libmxtpu_io.so")

_lib = None
_lib_lock = threading.Lock()
_build_error: Optional[str] = None


_jpeg_build_error: Optional[str] = None


def _build() -> Optional[str]:
    """Build the native library; tries recordio + libjpeg decode first,
    falls back to recordio-only when libjpeg headers are absent (jpeg
    support is then detected via hasattr on the loaded library; the jpeg
    attempt's compiler error is kept in _jpeg_build_error for
    diagnostics)."""
    global _jpeg_build_error
    base = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread"]
    attempts = []
    if os.path.exists(_SRC_JPEG):
        attempts.append((base + [_SRC, _SRC_JPEG, "-o", _LIB_PATH, "-ljpeg"],
                         True))
    attempts.append((base + [_SRC, "-o", _LIB_PATH], False))
    err = "no build attempted"
    for cmd, with_jpeg in attempts:
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=120)
        except (OSError, subprocess.TimeoutExpired) as e:
            err = str(e)
        else:
            if res.returncode == 0:
                return None
            err = res.stderr[-2000:]
        if with_jpeg:
            _jpeg_build_error = err
    return err


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native IO library; None if unavailable."""
    global _lib, _build_error
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        srcs = [s for s in (_SRC, _SRC_JPEG) if os.path.exists(s)]
        stale = os.path.exists(_LIB_PATH) and srcs and \
            max(os.path.getmtime(s) for s in srcs) > \
            os.path.getmtime(_LIB_PATH)
        if not os.path.exists(_LIB_PATH) or stale:
            if not os.path.exists(_SRC):
                _build_error = "source missing"
                return None
            err = _build()
            if err is not None:
                _build_error = err
                return None
        lib = ctypes.CDLL(_LIB_PATH)
        lib.rio_index_build.restype = ctypes.c_int64
        lib.rio_index_build.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                        ctypes.c_void_p, ctypes.c_int64]
        lib.rio_reader_create.restype = ctypes.c_void_p
        lib.rio_reader_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                          ctypes.c_int, ctypes.c_uint64]
        lib.rio_reader_next.restype = ctypes.c_int64
        lib.rio_reader_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                        ctypes.c_int64]
        lib.rio_reader_peek_len.restype = ctypes.c_int64
        lib.rio_reader_peek_len.argtypes = [ctypes.c_void_p]
        lib.rio_reader_next_batch.restype = ctypes.c_int64
        lib.rio_reader_next_batch.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                              ctypes.c_void_p, ctypes.c_int64,
                                              ctypes.c_void_p]
        lib.rio_reader_reset.argtypes = [ctypes.c_void_p]
        lib.rio_reader_destroy.argtypes = [ctypes.c_void_p]
        lib.rio_writer_create.restype = ctypes.c_void_p
        lib.rio_writer_create.argtypes = [ctypes.c_char_p]
        lib.rio_writer_write.restype = ctypes.c_int64
        lib.rio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_int64]
        lib.rio_writer_destroy.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "jdec_create"):
            lib.jdec_create.restype = ctypes.c_void_p
            lib.jdec_create.argtypes = [
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
                ctypes.c_void_p, ctypes.c_void_p]
            lib.jdec_decode_batch.restype = ctypes.c_int64
            lib.jdec_decode_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
            lib.jdec_reset.argtypes = [ctypes.c_void_p]
            lib.jdec_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def build_error() -> Optional[str]:
    get_lib()
    return _build_error


def build_index(path: str) -> Tuple[_np.ndarray, _np.ndarray]:
    """Scan a .rec file -> (offsets, lengths) int64 arrays."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError(f"native IO unavailable: {_build_error}")
    n = lib.rio_index_build(path.encode(), None, None, 0)
    if n < 0:
        raise IOError(f"cannot scan record file {path}")
    offs = _np.zeros(n, _np.int64)
    lens = _np.zeros(n, _np.int64)
    if n:
        # capacity-bounded: a concurrently growing file can't overflow
        m = lib.rio_index_build(path.encode(), offs.ctypes.data,
                                lens.ctypes.data, n)
        if m < 0:
            raise IOError(f"record file {path} became unreadable mid-scan")
        offs, lens = offs[:m], lens[:m]
    return offs, lens


class NativeRecordReader:
    """Background-prefetching record reader over a .rec file.

    The C++ worker thread reads ahead into a bounded ring (capacity records)
    so file IO overlaps Python-side decode and device work — the
    PrefetcherIter design (reference src/io/iter_prefetcher.h:47) without a
    GIL in the hot path. shuffle=True re-orders records each epoch.
    """

    def __init__(self, path: str, capacity: int = 256, shuffle: bool = False,
                 seed: int = 0, max_record: int = 1 << 24):
        lib = get_lib()
        if lib is None:
            raise RuntimeError(f"native IO unavailable: {_build_error}")
        self._lib = lib
        self._handle = lib.rio_reader_create(path.encode(), capacity,
                                             1 if shuffle else 0, seed)
        if not self._handle:
            raise IOError(f"cannot open record file {path}")
        self._buf = bytearray(max_record)
        self._cbuf = (ctypes.c_char * max_record).from_buffer(self._buf)

    def _check_open(self):
        if not self._handle:
            raise ValueError("reader is closed")

    def next(self) -> Optional[bytes]:
        self._check_open()
        n = self._lib.rio_reader_next(self._handle, self._cbuf, len(self._buf))
        if n == -1:
            return None
        if n == -2:
            need = self._lib.rio_reader_peek_len(self._handle)
            self._buf = bytearray(int(need))
            self._cbuf = (ctypes.c_char * len(self._buf)).from_buffer(self._buf)
            n = self._lib.rio_reader_next(self._handle, self._cbuf,
                                          len(self._buf))
            if n < 0:
                return None
        return bytes(self._buf[:n])

    def next_batch(self, n: int) -> List[bytes]:
        self._check_open()
        sizes = _np.zeros(n, _np.int64)
        got = self._lib.rio_reader_next_batch(self._handle, n, self._cbuf,
                                              len(self._buf), sizes.ctypes.data)
        if got == -2:  # first queued record exceeds the buffer: regrow
            need = self._lib.rio_reader_peek_len(self._handle)
            self._buf = bytearray(int(need))
            self._cbuf = (ctypes.c_char * len(self._buf)).from_buffer(self._buf)
            got = self._lib.rio_reader_next_batch(self._handle, n, self._cbuf,
                                                  len(self._buf),
                                                  sizes.ctypes.data)
        out, off = [], 0
        for i in range(int(got)):
            ln = int(sizes[i])
            out.append(bytes(self._buf[off:off + ln]))
            off += ln
        return out

    def reset(self):
        self._check_open()
        self._lib.rio_reader_reset(self._handle)

    def close(self):
        if self._handle:
            h, self._handle = self._handle, None
            self._lib.rio_reader_destroy(h)

    def __iter__(self):
        while True:
            rec = self.next()
            if rec is None:
                return
            yield rec

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordWriter:
    def __init__(self, path: str):
        lib = get_lib()
        if lib is None:
            raise RuntimeError(f"native IO unavailable: {_build_error}")
        self._lib = lib
        self._handle = lib.rio_writer_create(path.encode())
        if not self._handle:
            raise IOError(f"cannot open {path} for writing")

    def write(self, buf: bytes) -> int:
        """Returns the record's byte offset (for .idx files)."""
        if not self._handle:
            raise ValueError("writer is closed")
        if len(buf) >= (1 << 29):
            raise ValueError("record too large (>= 512 MB)")
        pos = self._lib.rio_writer_write(self._handle, buf, len(buf))
        if pos < 0:
            raise IOError("record write failed")
        return int(pos)

    def close(self):
        if self._handle:
            h, self._handle = self._handle, None
            self._lib.rio_writer_destroy(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def jpeg_available() -> bool:
    lib = get_lib()
    return lib is not None and hasattr(lib, "jdec_create")


class NativeJpegDecoder:
    """Batch JPEG decode + resize-short + crop + mirror + normalize in C++
    (reference iter_image_recordio_2.cc threaded decode pipeline). One call
    per batch; the internal pthread pool runs with the GIL released, so
    Python-side prefetch fully overlaps."""

    def __init__(self, out_h: int, out_w: int, resize_short: int = 0,
                 rand_crop: bool = False, rand_mirror: bool = False,
                 seed: int = 0, nthreads: int = 4,
                 mean=(0.0, 0.0, 0.0), std=(1.0, 1.0, 1.0)):
        lib = get_lib()
        if lib is None or not hasattr(lib, "jdec_create"):
            raise RuntimeError(
                "native JPEG decode unavailable: "
                f"{_build_error or _jpeg_build_error or 'libjpeg build not attempted (jpegdec.cc missing)'}")
        self._lib = lib
        self._hw = (out_h, out_w)
        m = (ctypes.c_float * 3)(*[float(x) for x in mean])
        s = (ctypes.c_float * 3)(*[float(x) for x in std])
        self._handle = lib.jdec_create(out_h, out_w, int(resize_short),
                                       1 if rand_crop else 0,
                                       1 if rand_mirror else 0,
                                       int(seed) & (2 ** 64 - 1),
                                       int(nthreads), m, s)

    def decode_batch(self, payloads) -> Tuple[_np.ndarray, _np.ndarray]:
        """payloads: list[bytes] -> (float32 (n,3,H,W) CHW, ok bool (n,))."""
        if not self._handle:
            raise ValueError("decoder is closed")
        n = len(payloads)
        h, w = self._hw
        out = _np.empty((n, 3, h, w), _np.float32)
        ok = _np.zeros(n, _np.int8)
        lens = _np.array([len(p) for p in payloads], _np.int64)
        blob = b"".join(payloads)
        self._lib.jdec_decode_batch(self._handle, n, blob,
                                    lens.ctypes.data, out.ctypes.data,
                                    ok.ctypes.data)
        return out, ok.astype(bool)

    def reset(self):
        if self._handle:
            self._lib.jdec_reset(self._handle)

    def close(self):
        if self._handle:
            h, self._handle = self._handle, None
            self._lib.jdec_destroy(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
